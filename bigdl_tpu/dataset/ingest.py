"""Streaming stage-pipelined ingest engine: the real-data hot path.

Reference equivalent: ``dataset/image/MTLabeledBGRImgToBatch.scala:46`` —
the production ImageNet lesson that host-side batch prep must overlap both
itself (decode vs assemble) and device compute.  The synchronous
:class:`~bigdl_tpu.dataset.mt_batch.MTLabeledBGRImgToBatch` executes
read → decode → assemble serially *per batch* (``pool.map`` is a batch
barrier, assemble runs while the pool sits idle); BENCH_r05 measured that
structure at 0.56x of the decode-alone ceiling.  This module removes the
barriers:

    sharded seqfile readers ──► record ring ──► decode pool ──► ordered
    decoded window ──► assembler (native pack, GIL-released) ──► batch
    ring ──► consumer (── engine.BatchPrefetcher keeps N device uploads
    in flight beyond this point)

Every stage is decoupled by a bounded ring (backpressure, never unbounded
memory) and instrumented: items, busy seconds, stall seconds split into
*starve* (waiting for the upstream stage) and *backpressure* (blocked on a
full downstream ring), plus mean ring occupancy.  ``stats()`` snapshots
feed ``bench.py --ingest-only`` (``bench_ingest.json``) and the training
summary layer — the stage with high busy and low stall is the bottleneck.

Determinism contract (the part that makes this usable for training, not
just benchmarks): crop offsets / flips draw from a CLONE of the caller's
``RandomGenerator`` stream in strict record order, and each batch carries
the post-draw RNG state; the clone's position is committed back to the
caller's stream only when the batch is CONSUMED.  Pipeline read-ahead that
gets discarded (an epoch rollover replacing the chain) therefore never
advances the user-visible stream — the pipelined engine reproduces the
synchronous path's batch sequence bit for bit at every depth setting, and
epoch rollover / reshuffle stays producer-owned exactly as before
(``engine.BatchPrefetcher``'s single-drawer contract).  With MULTIPLE
engines forked from one stream (a multi-shard ``ShardedDataSet``), only
the first fork commits; the others draw decorrelated deterministic
per-shard streams (the reference's per-partition RNG model) — sync-path
bit-parity is a single-engine contract, multi-shard runs are run-to-run
deterministic.

Self-healing contract (the part that makes this usable on dirty data at
production scale): stage failures are CLASSIFIED.  *Data* faults — a
corrupt/truncated SequenceFile record, an undecodable image, an
undersized frame — skip the one offending record into a bounded
:class:`RecordQuarantine` (``bigdl.ingest.maxBadRecords``; budget
exceeded or budget 0 → fail loudly, with a sample of offenders), counted
through the metrics registry (``Ingest/quarantined``) so silent data
loss is impossible.  *Infrastructure* faults — a transient IO blip, a
dead stage thread, a wedged ring — are retried/restarted: record reads
run behind ``utils.file_io``'s capped-backoff transient retry, a
:class:`_StageSupervisor` restarts a silently-dead reader/assembler
thread (bounded by ``bigdl.ingest.maxStageRestarts``, then escalates to
:class:`IngestInfraError`), and per-ring progress heartbeats detect a
wedged handoff (``bigdl.ingest.stallTimeoutSec``) so the run aborts with
per-stage diagnostics instead of hanging forever.  As graceful
degradation, ``bigdl.ingest.fallbackOnFailure`` lets a supervisor-
declared-dead engine finish the epoch on the synchronous path — same
drawer RNG, so the batch stream continues bit-identically (modulo
quarantined records).  All of it is provable on CPU via the chaos
injectors (``bigdl.chaos.corruptRecordAt`` / ``failDecodeAt`` /
``killStageThread`` / ``transientReads``, ``utils/chaos.py``).

Configuration (``bigdl.ingest.*``, see ``utils/config.py``):

===============================  =============================================
``bigdl.ingest.shards``          parallel seqfile reader threads
``bigdl.ingest.decodeWorkers``   decode pool size (default: host cores)
``bigdl.ingest.recordRingDepth`` reader → decode record ring depth
``bigdl.ingest.decodedRingDepth``in-flight decode window (default 2x batch)
``bigdl.ingest.batchRingDepth``  assembled batches buffered ahead
``bigdl.ingest.batchesInFlight`` device uploads in flight (BatchPrefetcher)
``bigdl.ingest.maxBadRecords``   data-error quarantine budget (0 = fail fast)
``bigdl.ingest.maxStageRestarts``dead-stage restarts before escalation
``bigdl.ingest.fallbackOnFailure`` dead engine → sync path mid-epoch
``bigdl.ingest.stallTimeoutSec`` wedged-ring detection window (0 = off)
``bigdl.ingest.deviceAugment``   pack FULL u8 frames + ride-along crop
                                 offsets/flips; crop/flip/transpose runs
                                 on device (``nn.DeviceAugment``)
``bigdl.ingest.autoscale.*``     supervisor-driven decode/assemble worker
                                 scaling (:class:`AutoscalePolicy`)
``bigdl.ingest.epochCache*``     decoded-frame cache across epochs
                                 (``dataset/epoch_cache.py``)
``bigdl.ingest.zeroCopyUpload``  dlpack handoff at ``engine.to_device``
===============================  =============================================
"""

from __future__ import annotations

import os
import queue
import threading
import time
import weakref
from collections import deque
from concurrent import futures
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu import telemetry
from bigdl_tpu import analysis
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.resources import GOVERNOR as _governor
from bigdl_tpu.resources import item_nbytes as _item_nbytes
from bigdl_tpu.utils import config

#: live engines, for the summary layer (weak: an abandoned engine must not
#: be pinned by the diagnostics that observe it)
_LIVE: "weakref.WeakSet" = weakref.WeakSet()

_END = object()          # upstream exhausted
_NO_ITEM = object()      # try_get on an empty ring

_NAME_LOCK = analysis.make_lock("ingest.name")
_NAME_SEQ = [0]          # per-process engine naming (ingest0, ingest1, …)


# ---------------------------------------------------------------------------
# error taxonomy + quarantine
# ---------------------------------------------------------------------------

class IngestDataError(Exception):
    """A fault in the DATA, not the machinery: corrupt/truncated record,
    undecodable image, undersized frame.  Quarantinable — skipping the
    one record is correct; retrying it is not (corrupt bytes stay
    corrupt)."""

    #: never absorbed by a transient-IO retry (``file_io._is_transient``)
    fatal = True


class IngestInfraError(RuntimeError):
    """The ingest MACHINERY failed beyond its self-healing budget: a
    stage thread died ``maxStageRestarts + 1`` times, or a ring wedged
    past ``stallTimeoutSec``.  Carries the engine's last per-stage
    ``stats()`` snapshot in ``diagnosis`` so the failure names the sick
    stage, not just the symptom."""

    def __init__(self, message: str, diagnosis: Optional[dict] = None):
        super().__init__(message)
        self.diagnosis = diagnosis or {}


class IngestStallError(IngestInfraError):
    """No ring made progress for ``bigdl.ingest.stallTimeoutSec`` while
    the consumer was blocked waiting — a wedged handoff (dead producer +
    blocked consumer), detected instead of hung."""


class QuarantineExceededError(IngestInfraError):
    """More data errors than ``bigdl.ingest.maxBadRecords`` allows: the
    data set is dirtier than the operator budgeted for, and silently
    skipping an unbounded stream of records would train on a different
    distribution than requested.  The message carries a sample of the
    offenders."""


def _is_data_error(e: BaseException) -> bool:
    """Data-vs-infrastructure classification shared by every stage."""
    from bigdl_tpu.dataset.seqfile import CorruptRecordError
    from bigdl_tpu.utils.chaos import CorruptRecord, UndecodableImage
    return isinstance(e, (IngestDataError, CorruptRecordError,
                          CorruptRecord, UndecodableImage))


class _StageKilledError(RuntimeError):
    """Chaos-injected silent death of a decode worker: an INFRA fault
    the assembler answers by resubmitting the decode (bounded), never by
    quarantining the record (its bytes are fine)."""


class RecordQuarantine:
    """Bounded sink for data-error records.

    ``admit(stage, index, name, error)`` either swallows the fault
    (budget remaining: count it, sample it, bump the registry counters)
    or raises — the ORIGINAL error when the budget is 0 (quarantine
    disabled: today's fail-fast contract, bit-parity with the sync
    path), a :class:`QuarantineExceededError` naming a sample of
    offenders when a nonzero budget runs out.  Thread-safe: read,
    decode, and assemble stages admit concurrently."""

    SAMPLE_MAX = 8

    def __init__(self, budget: Optional[int] = None):
        if budget is None:
            budget = config.get_int("bigdl.ingest.maxBadRecords", 0)
        self.budget = int(budget)
        self.count = 0
        self.by_stage: dict = {}
        self.samples: List[dict] = []
        self._lock = analysis.make_lock("ingest.quarantine")

    def admit(self, stage: str, index: Optional[int], name: Optional[str],
              error: BaseException) -> None:
        if self.budget <= 0:
            raise error
        with self._lock:
            self.count += 1
            self.by_stage[stage] = self.by_stage.get(stage, 0) + 1
            if len(self.samples) < self.SAMPLE_MAX:
                sample = {"stage": stage, "index": index, "name": name,
                          "error": repr(error)}
                self.samples.append(sample)
                # even this bounded diagnostic sink is accounted: the
                # host-memory governor's roll-up must see every buffer
                _governor.account("ingest_quarantine").add(
                    _item_nbytes(sample))
            over = self.count > self.budget
        telemetry.counter(
            "Ingest/quarantined", summary=True,
            help="data-error records skipped by the ingest quarantine"
        ).inc()
        telemetry.counter("Ingest/stage_errors", labels={"stage": stage},
                          help="data errors observed per ingest stage").inc()
        if over:
            raise QuarantineExceededError(
                f"ingest quarantine budget exhausted: {self.count} bad "
                f"records > bigdl.ingest.maxBadRecords={self.budget}; "
                f"offender sample: {self.samples}",
                diagnosis={"quarantine": self.summary()}) from error
        import logging
        logging.getLogger("bigdl_tpu").warning(
            "ingest quarantined record %s (%s) at stage %s: %r "
            "[%d/%d budget]", index, name, stage, error, self.count,
            self.budget)

    def summary(self) -> dict:
        with self._lock:
            return {"count": self.count, "budget": self.budget,
                    "by_stage": dict(self.by_stage),
                    "samples": list(self.samples)}


class _StageSupervisor:
    """Monitor for the engine's stage threads and ring heartbeats.

    Each restartable stage registers a thread factory and a done flag;
    the monitor polls: a thread that is dead with its done flag unset
    (it neither finished nor surfaced an error — a silent crash) is
    restarted from shared stage state, up to ``max_restarts`` times,
    then the engine is DECLARED DEAD: ``failure`` is set and ``failed``
    signaled so the blocked consumer wakes immediately.  With
    ``stall_timeout`` > 0 the monitor also watches ring progress
    heartbeats: no ring progressing while the consumer is blocked
    waiting means a wedged handoff — declared dead with the per-stage
    stats in the error instead of hanging forever."""

    POLL_S = 0.02

    def __init__(self, max_restarts: int, stall_timeout: float,
                 diagnose, rings: Sequence["_Ring"],
                 run_stats: Optional[dict] = None,
                 autoscale=None, autoscale_interval: float = 0.25):
        self.max_restarts = max(0, int(max_restarts))
        self.stall_timeout = float(stall_timeout)
        #: autoscale tick: called every ``autoscale_interval`` from the
        #: monitor loop (restart + scaling share one supervisor — the
        #: stage-lifecycle authority).  A failing tick disables itself
        #: rather than killing a working engine.
        self._autoscale = autoscale
        self._autoscale_interval = max(0.01, float(autoscale_interval))
        self._autoscale_due = time.monotonic() + self._autoscale_interval
        self._diagnose = diagnose          # () -> stats dict, for errors
        #: THIS run's StageStats (progress source for the stall check —
        #: the engine-wide diagnose merge would let a sibling shard
        #: run's progress mask this run's wedge)
        self._run_stats = run_stats or {}
        self._rings = list(rings)
        self._stages: dict = {}
        self.failure: Optional[BaseException] = None   # guarded-by: _lock
        self.failed = threading.Event()
        self.consumer_waiting_since: Optional[float] = None
        self._last_items = -1
        self._last_items_at: Optional[float] = None
        self.restarts = 0                              # guarded-by: _lock
        self._lock = analysis.make_lock("ingest.supervisor")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, name: str, factory, done_flag: List[bool]) -> None:
        """Track a stage: ``factory()`` builds AND STARTS a replacement
        thread (resuming from the stage's shared state); ``done_flag[0]``
        is set by the stage on any orderly exit — completion or error
        surfaced downstream — and gates restarts."""
        self._stages[name] = {"factory": factory, "thread": factory(),
                              "done": done_flag, "restarts": 0}

    def thread(self, name: str) -> threading.Thread:
        return self._stages[name]["thread"]

    def declare_failed(self, error: BaseException) -> None:
        with self._lock:
            if self.failure is None:
                self.failure = error
        self.failed.set()

    def count_restart(self, stage: str) -> None:
        with self._lock:
            self.restarts += 1
        telemetry.counter(
            "Ingest/stage_restarts", labels={"stage": stage},
            help="dead ingest stage workers restarted by the "
                 "supervisor").inc()

    def start(self) -> "_StageSupervisor":
        self._thread = threading.Thread(target=self._monitor, daemon=True,
                                        name="ingest-supervisor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    # -- monitor ----------------------------------------------------------

    def _monitor(self) -> None:
        import logging
        logger = logging.getLogger("bigdl_tpu")
        while not self._stop.wait(self.POLL_S):
            if self.failure is not None:
                return
            try:
                self._poll_once(logger)
            except BaseException as e:
                # the monitor must NEVER die silently: with it gone the
                # consumer would block on sup.failed forever — exactly
                # the hang this thread exists to prevent.  A failing
                # restart factory (thread exhaustion) or diagnose call
                # becomes an engine failure instead.
                self.declare_failed(IngestInfraError(
                    f"ingest supervisor failed: {e!r}"))
                return
            if self.failure is not None:
                return

    def _poll_once(self, logger) -> None:
        for name, st in self._stages.items():
            if st["done"][0] or st["thread"].is_alive():
                continue
            # dead without an orderly exit: a silent crash
            if st["restarts"] >= self.max_restarts:
                self.declare_failed(IngestInfraError(
                    f"ingest stage '{name}' died "
                    f"{st['restarts'] + 1} time(s) (restart budget "
                    f"bigdl.ingest.maxStageRestarts="
                    f"{self.max_restarts} exhausted)",
                    diagnosis=self._diagnose()))
                return
            st["restarts"] += 1
            self.count_restart(name)
            logger.warning(
                "ingest stage '%s' thread died silently — "
                "restarting from shared stage state (%d/%d)",
                name, st["restarts"], self.max_restarts)
            st["thread"] = st["factory"]()
        if self.stall_timeout > 0:
            self._check_stall()
        if self._autoscale is not None:
            now = time.monotonic()
            if now >= self._autoscale_due:
                self._autoscale_due = now + self._autoscale_interval
                try:
                    self._autoscale()
                except BaseException as e:
                    # scaling is an optimization, never a failure mode:
                    # a tick that cannot act (thread exhaustion on a
                    # spawn, …) logs once and stops trying
                    logger.warning(
                        "ingest autoscaler disabled after error: %r", e)
                    self._autoscale = None

    def _check_stall(self) -> None:
        waiting = self.consumer_waiting_since
        if waiting is None:
            return
        now = time.monotonic()
        if now - waiting < self.stall_timeout:
            return
        newest = max((r.last_progress for r in self._rings),
                     default=0.0)
        if now - newest < self.stall_timeout:
            return
        # ring silence alone is not a wedge: a slow stage working a big
        # item (a long assemble with full record ring + empty batch
        # ring) heartbeats no ring but still COMPLETES items — consult
        # THIS run's per-stage item counters before declaring death
        # (this run's own stats, not the engine-wide merge: a sibling
        # shard run's progress must not mask this run's wedge)
        items = sum(s.items for s in self._run_stats.values())
        if items != self._last_items or self._last_items_at is None:
            self._last_items = items
            self._last_items_at = now
            return
        if now - self._last_items_at < self.stall_timeout:
            return
        self.declare_failed(IngestStallError(
            f"ingest wedged: no ring progressed for "
            f"{now - newest:.1f}s and no stage completed an item for "
            f"{now - self._last_items_at:.1f}s while the consumer was "
            f"blocked (bigdl.ingest.stallTimeoutSec={self.stall_timeout});"
            " per-stage stats in .diagnosis name the stuck handoff",
            diagnosis=self._diagnose()))


class StageStats:
    """Counters for one pipeline stage.

    ``items``/``busy_s`` measure the stage's own work; ``starve_s`` is time
    blocked waiting for its upstream ring, ``backpressure_s`` time blocked
    on a full downstream ring.  A stage whose starve dominates is fed too
    slowly (look upstream); one whose backpressure dominates is faster than
    its consumer (look downstream); the bottleneck stage shows near-zero
    stall and the highest busy fraction."""

    def __init__(self, name: str):
        self.name = name
        self._lock = analysis.make_lock("ingest.stage_stats")
        self.items = 0
        self.busy_s = 0.0
        self.starve_s = 0.0
        self.backpressure_s = 0.0
        self._occ_sum = 0
        self._occ_n = 0
        self._t0 = time.monotonic()

    def add(self, items: int = 0, busy_s: float = 0.0,
            starve_s: float = 0.0, backpressure_s: float = 0.0) -> None:
        with self._lock:
            self.items += items
            self.busy_s += busy_s
            self.starve_s += starve_s
            self.backpressure_s += backpressure_s

    def sample_occupancy(self, depth: int) -> None:
        with self._lock:
            self._occ_sum += depth
            self._occ_n += 1

    def stall_seconds(self) -> Tuple[float, float]:
        """(starve_s, backpressure_s) — the autoscaler's raw signals."""
        with self._lock:
            return self.starve_s, self.backpressure_s

    def snapshot(self) -> dict:
        with self._lock:
            wall = max(time.monotonic() - self._t0, 1e-9)
            return {
                "items": self.items,
                "throughput_per_sec": round(self.items / wall, 1),
                "busy_s": round(self.busy_s, 3),
                "starve_s": round(self.starve_s, 3),
                "backpressure_s": round(self.backpressure_s, 3),
                "stall_frac": round(
                    (self.starve_s + self.backpressure_s) / wall, 3),
                "mean_queue_depth": round(self._occ_sum / self._occ_n, 2)
                if self._occ_n else 0.0,
            }


class _Ring:
    """Bounded stage-coupling queue with stall accounting.

    ``put`` charges blocked time to the producing stage's ``backpressure_s``
    (a full ring means the downstream stage is the bottleneck); ``get``
    charges the consuming stage's ``starve_s``.  Both poll a stop event so
    teardown can never deadlock a stage thread.

    ``limit`` is the DYNAMIC depth: it starts at the configured depth and
    the host-memory governor's shrinkers may halve it mid-run
    (:meth:`shrink`) — a ring at or above its limit behaves exactly like a
    full one, so the shrink flows through the existing backpressure
    accounting rather than a new mechanism.  ``account``/``sizer`` keep a
    governor byte ledger current as items enter and leave."""

    def __init__(self, depth: int, producer: Optional[StageStats] = None,
                 consumer: Optional[StageStats] = None,
                 account=None, sizer=None):
        depth = max(1, int(depth))
        self.q: "queue.Queue" = queue.Queue(maxsize=depth)
        #: dynamic depth cap, <= the queue's hard maxsize; shrinks persist
        self.limit = depth
        self._producer = producer
        self._consumer = consumer
        self._account = account
        self._sizer = sizer
        #: progress heartbeat: monotonic time of the last successful
        #: put/get — the stage supervisor's wedged-handoff signal and
        #: the watchdog's stall diagnostic (ring age)
        self._hb_lock = analysis.make_lock("ingest.ring")
        self.last_progress = time.monotonic()    # guarded-by: _hb_lock

    def shrink(self) -> int:
        """Halve the dynamic depth (floor 1); returns the new limit."""
        self.limit = max(1, self.limit // 2)
        return self.limit

    def _charge(self, item, sign: int) -> None:
        if self._account is None:
            return
        try:
            n = self._sizer(item) if self._sizer is not None else 0
        except Exception:       # accounting must never break the stage
            n = 0
        if n:
            (self._account.add if sign > 0 else self._account.sub)(n)

    def put(self, item, stop: Optional[threading.Event]) -> bool:
        t0 = None
        while stop is None or not stop.is_set():
            if self.q.qsize() >= self.limit:
                # at (or shrunk below) the dynamic depth: identical to a
                # full queue — wait, charging backpressure
                if t0 is None:
                    t0 = time.monotonic()
                if stop is None:
                    time.sleep(0.05)
                else:
                    stop.wait(0.05)
                continue
            try:
                self.q.put(item, timeout=0.05)
            except queue.Full:
                if t0 is None:
                    t0 = time.monotonic()
                continue
            with self._hb_lock:
                self.last_progress = time.monotonic()
            self._charge(item, +1)
            if t0 is not None and self._producer is not None:
                # StageStats is internally locked: .add() is thread-safe
                self._producer.add(backpressure_s=time.monotonic() - t0)  # lint: allow(missing-guarded-by)
            if self._producer is not None:
                self._producer.sample_occupancy(self.q.qsize())
            return True
        if t0 is not None and self._producer is not None:
            self._producer.add(backpressure_s=time.monotonic() - t0)
        return False

    def get(self, stop: Optional[threading.Event]):
        t0 = None
        while stop is None or not stop.is_set():
            try:
                item = self.q.get(timeout=0.05)
                with self._hb_lock:
                    self.last_progress = time.monotonic()
                self._charge(item, -1)
                if t0 is not None and self._consumer is not None:
                    # StageStats is internally locked: .add() is thread-safe
                    self._consumer.add(starve_s=time.monotonic() - t0)  # lint: allow(missing-guarded-by)
                return item
            except queue.Empty:
                if t0 is None:
                    t0 = time.monotonic()
        if t0 is not None and self._consumer is not None:
            self._consumer.add(starve_s=time.monotonic() - t0)
        return _NO_ITEM

    def try_get(self):
        try:
            item = self.q.get_nowait()
        except queue.Empty:
            return _NO_ITEM
        self._charge(item, -1)
        return item

    def drain(self) -> None:
        try:
            while True:
                item = self.q.get_nowait()
                self._charge(item, -1)
        except queue.Empty:
            pass


class _DecodePool:
    """Resizable decode worker pool — the stage autoscaler's actuator.

    ``concurrent.futures.ThreadPoolExecutor`` can grow its pool but
    never shrink it; the autoscaler needs both directions.  Workers
    pull ``(future, fn, args)`` tickets from an internal queue and
    resolve real :class:`concurrent.futures.Future` objects, so every
    call site written against the executor API (``submit``,
    ``Future.result``, ``shutdown(cancel_futures=True)``) works
    unchanged — including the assembler's dead-decode-worker resubmit
    path, which observes exceptions through the future exactly as with
    the executor.  ``set_workers`` retires surplus workers
    cooperatively: each worker re-checks the target between tickets and
    exits when the pool is over target; a mid-decode worker finishes
    its ticket first, so no decode is ever abandoned by a scale-down.

    The ticket queue is unbounded by construction but bounded in
    practice: the assembler's decode window (``decoded_ring_depth``,
    governor-shrinkable) is the only submitter and never holds more
    than ``window`` tickets in flight."""

    def __init__(self, workers: int, thread_name_prefix: str = "decode"):
        self._tickets: "queue.Queue" = queue.Queue()
        self._prefix = thread_name_prefix
        self._lock = analysis.make_lock("ingest.decode_pool")
        self._target = max(1, int(workers))     # guarded-by: _lock
        self._alive = 0                         # guarded-by: _lock
        self._seq = 0                           # guarded-by: _lock
        self._shutdown = False                  # guarded-by: _lock
        for _ in range(self._target):
            self._spawn()

    def _spawn(self) -> None:
        with self._lock:
            self._alive += 1
            self._seq += 1
            name = f"{self._prefix}-{self._seq}"
        t = threading.Thread(target=self._worker, daemon=True, name=name)
        t.start()

    def _worker(self) -> None:
        while True:
            with self._lock:
                if self._shutdown or self._alive > self._target:
                    self._alive -= 1
                    return
            try:
                ticket = self._tickets.get(timeout=0.1)
            except queue.Empty:
                continue
            fut, fn, args = ticket
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args))
            except BaseException as e:
                # surfaces at Future.result() on the assembler — same
                # taxonomy routing as the executor path
                fut.set_exception(e)

    @property
    def workers(self) -> int:
        return self._target

    def set_workers(self, n: int) -> int:
        """Resize toward ``n`` (floor 1); returns the new target.
        Growth spawns immediately; shrink is cooperative (workers exit
        between tickets, never mid-decode)."""
        n = max(1, int(n))
        with self._lock:
            if self._shutdown:
                return self._target
            grow = n - self._target
            self._target = n
        for _ in range(grow):
            self._spawn()
        return n

    def submit(self, fn, *args) -> "futures.Future":
        fut: "futures.Future" = futures.Future()
        self._tickets.put((fut, fn, args))
        return fut

    def shutdown(self, wait: bool = False,
                 cancel_futures: bool = False) -> None:
        with self._lock:
            self._shutdown = True
        if cancel_futures:
            try:
                while True:
                    fut, _fn, _args = self._tickets.get_nowait()
                    fut.cancel()
            except queue.Empty:
                pass


class AutoscalePolicy:
    """Deterministic hysteresis policy for ingest stage autoscaling.

    Pure state machine — no clocks, no randomness: a fixed sequence of
    signal samples always produces the same action sequence (asserted
    by tests/test_ingest.py), so autoscaling can never make a run
    nondeterministic in anything but wall-clock.

    Per :meth:`decide` call (one per ``bigdl.ingest.autoscale.
    intervalSec`` interval), the signals are the assemble stage's
    starve and backpressure FRACTIONS over the interval just ended:
    starve = the assembler waited on decode (the scale-UP signal),
    backpressure = the batch ring was full, i.e. the consumer is the
    bottleneck and more decode workers cannot help (a scale-DOWN
    signal).  ``patience`` consecutive same-direction signals are
    required before acting; after an action the policy holds for
    ``cooldown`` intervals so the new worker count's effect is measured
    before the next decision.  The host-memory governor is the upper-
    bound authority: under pressure the policy never scales up and
    steps down toward the floor."""

    def __init__(self, min_workers: int, max_workers: int,
                 up_starve_frac: float, down_starve_frac: float,
                 patience: int, cooldown: int):
        self.min_workers = max(1, int(min_workers))
        self.max_workers = max(self.min_workers, int(max_workers))
        self.up_starve_frac = float(up_starve_frac)
        self.down_starve_frac = float(down_starve_frac)
        self.patience = max(1, int(patience))
        self.cooldown = max(0, int(cooldown))
        self._up_streak = 0
        self._down_streak = 0
        self._hold = 0

    def decide(self, starve_frac: float, backpressure_frac: float,
               workers: int, under_pressure: bool = False) -> int:
        """One interval's decision: +1 add a worker, -1 retire one, 0
        hold."""
        if self._hold > 0:
            self._hold -= 1
            return 0
        down = (workers > self.min_workers and
                (under_pressure or
                 starve_frac <= self.down_starve_frac or
                 backpressure_frac >= self.up_starve_frac))
        up = (not down and not under_pressure and
              workers < self.max_workers and
              starve_frac >= self.up_starve_frac)
        if up:
            self._up_streak += 1
            self._down_streak = 0
        elif down:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        if self._up_streak >= self.patience:
            self._up_streak = 0
            self._hold = self.cooldown
            return 1
        if self._down_streak >= self.patience:
            self._down_streak = 0
            self._hold = self.cooldown
            return -1
        return 0


class ShardedSeqFileReader:
    """Parallel SequenceFile record source preserving the global order.

    ``shards`` reader threads (``bigdl.ingest.shards``) own the ``*.seq``
    files round-robin and stream records into per-shard rings; the merge
    side drains one file at a time in sorted-walk order, so the yielded
    sequence is byte-identical to a sequential
    :func:`~bigdl_tpu.dataset.seqfile.read_image_seqfile` sweep — sharding
    is a latency detail, not an ordering change.  IO and vint/frame parsing
    for file k+1..k+shards overlap the consumer's handling of file k."""

    def __init__(self, path: str, shards: Optional[int] = None,
                 ring_depth: Optional[int] = None,
                 quarantine: Optional[RecordQuarantine] = None):
        if os.path.isdir(path):
            self.files: List[str] = []
            for root, _, files in sorted(os.walk(path)):
                for fname in sorted(files):
                    if fname.endswith(".seq"):
                        self.files.append(os.path.join(root, fname))
        else:
            self.files = [path]
        self.shards = max(1, shards if shards is not None
                          else config.get_int("bigdl.ingest.shards", 2))
        self.ring_depth = (ring_depth if ring_depth is not None
                           else config.get_int("bigdl.ingest.recordRingDepth", 256))
        self.stats = StageStats("seqfile_read")
        #: data-error sink; None = build one per sweep from
        #: ``bigdl.ingest.maxBadRecords`` (budget 0 keeps the historical
        #: fail-fast: corrupt record -> IOError on the merge side)
        self.quarantine = quarantine

    def _file_records(self, path: str,
                      quarantine: Optional[RecordQuarantine]) -> Iterator:
        """One file's (name, label, data) records, self-healing: corrupt
        records resync-skip into the quarantine (budget permitting), and
        a TRANSIENT read failure re-opens the file and resumes after the
        already-yielded prefix — the ``utils.file_io`` capped-backoff
        policy applied to a streaming read (``file_io.retrying`` itself
        wraps one call; a generator needs the resume)."""
        from bigdl_tpu.dataset.seqfile import (CorruptRecordError,
                                               read_image_seqfile,
                                               read_image_seqfile_resilient)
        from bigdl_tpu.utils import file_io

        attempts = max(1, config.get_int("bigdl.io.retryTimes", 3))
        base = config.get_float("bigdl.io.retryInterval", 0.1)
        yielded = 0
        attempt = 1
        resilient = False    # fast native path until the FIRST corruption
        # a transient failure REPLAYS the file from the top; corrupt
        # records are deterministic, so the replay re-encounters skips
        # already admitted — count events and admit only the new ones,
        # or every replay would burn quarantine budget twice
        skips = {"admitted": 0}
        while True:
            seen = 0
            pass_start = yielded
            pass_skips = [0]
            try:
                if resilient:
                    def on_skip(err, resume):
                        pass_skips[0] += 1
                        if pass_skips[0] > skips["admitted"]:
                            quarantine.admit("seqfile_read", None, path,
                                             err)
                            skips["admitted"] = pass_skips[0]
                    src = read_image_seqfile_resilient(path,
                                                       on_skip=on_skip)
                else:
                    src = read_image_seqfile(path)
                for rec in src:
                    seen += 1
                    if seen <= yielded:
                        continue     # replayed prefix after a retry
                    yield rec
                    yielded += 1
                return
            except CorruptRecordError as e:
                if (resilient or quarantine is None or
                        quarantine.budget <= 0):
                    raise            # fail-fast contract (budget 0)
                # dirty file discovered: replay through the resilient
                # Python reader, which resyncs past the damage and
                # admits each skip into the quarantine.  Clean files
                # never pay for this — they stay on the native reader.
                resilient = True
            except Exception as e:
                if yielded > pass_start:
                    # this pass made fresh progress before failing: the
                    # budget is per-blip, like file_io.retrying grants
                    # it per operation — not per lifetime of the file
                    attempt = 1
                if attempt >= attempts or not file_io._is_transient(e):
                    raise
                delay = base * (2.0 ** (attempt - 1))
                import logging
                logging.getLogger("bigdl_tpu").warning(
                    "transient seqfile read failure on %s (attempt "
                    "%d/%d, resuming after record %d in %.2fs): %r",
                    path, attempt, attempts, yielded, delay, e)
                file_io._sleep(delay)
                attempt += 1

    def __iter__(self) -> Iterator:
        from bigdl_tpu.dataset.image import LabeledImageBytes

        if not self.files:
            return
        quarantine = (self.quarantine if self.quarantine is not None
                      else RecordQuarantine())
        self.last_quarantine = quarantine   # observable after the sweep
        n = min(self.shards, len(self.files))
        stop = threading.Event()
        rings = [_Ring(max(1, self.ring_depth // n), producer=self.stats)
                 for _ in range(n)]
        file_end = object()

        def reader(si: int) -> None:
            try:
                for fi in range(si, len(self.files), n):
                    t0 = time.monotonic()
                    for name, label, data in self._file_records(
                            self.files[fi], quarantine):
                        t1 = time.monotonic()
                        self.stats.add(items=1, busy_s=t1 - t0)
                        telemetry.add_span_s("ingest/seqfile_read", t0, t1)
                        if not rings[si].put(
                                LabeledImageBytes(name, label, data), stop):
                            return
                        t0 = time.monotonic()
                    if not rings[si].put(file_end, stop):
                        return
            except BaseException as e:  # surfaced on the merge side
                rings[si].put(e, stop)

        threads = [threading.Thread(target=reader, args=(si,), daemon=True,
                                    name=f"ingest-seqread{si}")
                   for si in range(n)]
        for t in threads:
            t.start()
        try:
            for fi in range(len(self.files)):
                ring = rings[fi % n]
                while True:
                    item = ring.get(None)
                    if item is file_end:
                        break
                    if isinstance(item, BaseException):
                        raise item
                    yield item
        finally:
            stop.set()
            for ring in rings:
                ring.drain()
            for t in threads:
                t.join(timeout=5)
            for ring in rings:
                ring.drain()


class StreamingIngest(Transformer):
    """Compressed byte records → MiniBatches, stage-pipelined.

    Drop-in pipelined replacement for
    :class:`~bigdl_tpu.dataset.mt_batch.MTLabeledBGRImgToBatch` (same
    constructor surface, same output semantics — asserted bit-identical by
    ``tests/test_prefetch_determinism.py``), with the per-batch barriers
    removed:

    - a *reader* thread pulls upstream records into a bounded record ring;
    - a *decode pool* (``decode_workers`` threads; cv2/PIL JPEG decode
      releases the GIL) holds a sliding window of in-flight decodes that
      spans batch boundaries — decode of batch k+1 proceeds while batch k
      is being packed;
    - an *assembler* thread consumes decoded images in strict record
      order, draws crop/flip from the (cloned) RNG stream, and packs full
      batches with the native std::thread assembler (ctypes releases the
      GIL for the call, so packing overlaps the pool);
    - assembled MiniBatches buffer in a bounded *batch ring* the consumer
      drains, each carrying the RNG state to commit on consumption.

    Ring depths and pool width default from ``bigdl.ingest.*``; constructor
    arguments override per instance.
    """

    def __init__(self, batch_size: int, crop: Tuple[int, int] = (224, 224),
                 mean: Sequence[float] = (104.0, 117.0, 123.0),
                 std: Sequence[float] = (1.0, 1.0, 1.0),
                 random_crop: bool = True, hflip: bool = True,
                 device_normalize: bool = False,
                 device_augment: Optional[bool] = None,
                 device_jitter: bool = False,
                 decode_workers: Optional[int] = None,
                 record_ring_depth: Optional[int] = None,
                 decoded_ring_depth: Optional[int] = None,
                 batch_ring_depth: Optional[int] = None,
                 assemble_threads: Optional[int] = None,
                 name: Optional[str] = None,
                 max_bad_records: Optional[int] = None,
                 max_stage_restarts: Optional[int] = None,
                 fallback_on_failure: Optional[bool] = None,
                 stall_timeout: Optional[float] = None,
                 autoscale: Optional[bool] = None,
                 epoch_cache: Optional[bool] = None):
        if name is None:
            with _NAME_LOCK:
                name = f"ingest{_NAME_SEQ[0]}"
                _NAME_SEQ[0] += 1
        # distinguishes this engine's summary tags / log lines when more
        # than one engine is alive (train + validation pipelines, …)
        self.name = name
        self.batch_size = batch_size
        self.crop = crop
        self.mean, self.std = mean, std
        self.random_crop, self.hflip = random_crop, hflip
        self.device_normalize = device_normalize
        # device_augment: pack FULL uint8 NHWC frames plus ride-along
        # crop offsets/flips (drawn host-side from the clone-and-commit
        # stream, so parity with the host path is provable) and leave
        # crop/flip/transpose to nn.DeviceAugment inside the fused step.
        # Implies the uint8-upload layout: pair with nn.ChannelNormalize.
        self.device_augment = (
            device_augment if device_augment is not None
            else config.get_bool("bigdl.ingest.deviceAugment", False))
        # device_jitter: additionally ride along one int32 ColorJitter
        # seed per record, drawn from the same stream (breaks host-path
        # bit-parity by design — the host path has no jitter)
        self.device_jitter = bool(device_jitter)
        cores = max(1, os.cpu_count() or 1)
        self.decode_workers = (decode_workers if decode_workers is not None
                               else config.get_int("bigdl.ingest.decodeWorkers",
                                                   cores))
        self.record_ring_depth = (
            record_ring_depth if record_ring_depth is not None
            else config.get_int("bigdl.ingest.recordRingDepth", 256))
        self.decoded_ring_depth = (
            decoded_ring_depth if decoded_ring_depth is not None
            else config.get_int("bigdl.ingest.decodedRingDepth",
                                 2 * batch_size))
        self.batch_ring_depth = (
            batch_ring_depth if batch_ring_depth is not None
            else config.get_int("bigdl.ingest.batchRingDepth", 2))
        self.assemble_threads = assemble_threads or cores
        self.max_bad_records = (
            max_bad_records if max_bad_records is not None
            else config.get_int("bigdl.ingest.maxBadRecords", 0))
        self.max_stage_restarts = (
            max_stage_restarts if max_stage_restarts is not None
            else config.get_int("bigdl.ingest.maxStageRestarts", 2))
        self.fallback_on_failure = (
            fallback_on_failure if fallback_on_failure is not None
            else config.get_bool("bigdl.ingest.fallbackOnFailure", False))
        self.stall_timeout = (
            stall_timeout if stall_timeout is not None
            else config.get_float("bigdl.ingest.stallTimeoutSec", 0.0))
        self.autoscale = (
            autoscale if autoscale is not None
            else config.get_bool("bigdl.ingest.autoscale.enabled", True))
        #: live worker counts per stage (the Ingest/<stage>/workers
        #: gauges) and the autoscaler's action counters — mutated by the
        #: supervisor tick, read by summary_scalars and the driver's
        #: end-of-run decomposition log
        self.stage_workers = {"decode": self.decode_workers,
                              "assemble": self.assemble_threads}
        self.autoscale_events = {"up": 0, "down": 0}
        #: decoded-epoch cache, engine-lifetime (epoch 2 is a second run
        #: of the SAME transformer instance — the cache must outlive runs)
        use_cache = (epoch_cache if epoch_cache is not None
                     else config.get_bool("bigdl.ingest.epochCache", False))
        self.epoch_cache = None
        if use_cache:
            from bigdl_tpu.dataset.epoch_cache import DecodedEpochCache
            self.epoch_cache = DecodedEpochCache(
                name=self.name,
                cache_dir=config.get_property(
                    "bigdl.ingest.epochCacheDir"),
                budget_mb=config.get_int(
                    "bigdl.ingest.epochCacheBudgetMB", 0),
                segment_records=config.get_int(
                    "bigdl.ingest.epochCacheSegmentRecords", 256))
        # per-run stage stats: a ShardedDataSet applies ONE transformer
        # instance to every shard, so several runs can be live at once —
        # each run appends its own dict and stats() merges them
        self._active_stats: List[dict] = []
        self._last_stats: Optional[dict] = None
        #: latest run's quarantine / supervisor, for diagnostics + tests;
        #: _active_faults mirrors _active_stats (several shard runs can
        #: be live at once — monitoring must SUM them, not report the
        #: last-started run); run_history keeps a LIGHTWEIGHT summary
        #: dict per finished run ({"quarantine": ..., "stage_restarts":
        #: n}) so a multi-epoch soak can audit what epoch 1 quarantined
        #: without pinning dead threads/rings for the engine's lifetime
        self.quarantine: Optional[RecordQuarantine] = None
        self.supervisor: Optional[_StageSupervisor] = None
        self._active_faults: List[tuple] = []
        self._last_faults: Optional[tuple] = None
        self.run_history: List[dict] = []
        self.fallbacks = 0

    # ---- diagnostics ----------------------------------------------------

    def has_active_run(self) -> bool:
        """True while at least one pipeline run of this engine is live."""
        return bool(self._active_stats)

    def _fault_pairs(self) -> List[tuple]:
        """(quarantine, supervisor) of every ACTIVE run, else the last
        finished one — same merge contract as :meth:`stats`."""
        pairs = list(self._active_faults)
        if not pairs and self._last_faults is not None:
            pairs = [self._last_faults]
        return pairs

    def quarantined_count(self) -> int:
        """Data-error records skipped, summed over every active run."""
        return sum(q.count for q, _ in self._fault_pairs())

    def stage_restart_count(self) -> int:
        return sum(s.restarts for _, s in self._fault_pairs())

    def ring_ages(self) -> dict:
        """Seconds since each ring last made progress — the freshest
        (minimum) age across active runs, the wedged-handoff signal the
        supervisor and the watchdog diagnostics read; empty before the
        first run."""
        now = time.monotonic()
        out: dict = {}
        for _, sup in self._fault_pairs():
            for name, ring in zip(("record_ring", "batch_ring"),
                                  sup._rings):
                age = round(now - ring.last_progress, 3)
                out[name] = min(out.get(name, age), age)
        return out

    def fault_stats(self) -> dict:
        """Self-healing counters merged over the active runs (multi-
        shard pipelines sum, like :meth:`stats`): quarantine summary,
        stage restarts, fallbacks — the robustness sibling of
        :meth:`stats`."""
        pairs = self._fault_pairs()
        quarantine = {"count": sum(q.count for q, _ in pairs),
                      "samples": [s for q, _ in pairs
                                  for s in q.samples]}
        if len(pairs) == 1:
            quarantine = pairs[0][0].summary()
        return {
            "quarantine": quarantine if pairs else {},
            "stage_restarts": sum(s.restarts for _, s in pairs),
            "fallbacks": self.fallbacks,
            "ring_ages_s": self.ring_ages(),
        }

    def stats(self) -> dict:
        """Per-stage snapshots: the merge of every ACTIVE run (multi-shard
        pipelines sum their counters), else the last finished run."""
        runs = list(self._active_stats)
        if not runs and self._last_stats is not None:
            runs = [self._last_stats]
        if not runs:
            return {}
        if len(runs) == 1:
            return {name: s.snapshot() for name, s in runs[0].items()}
        out = {}
        for name in ("read", "decode", "assemble", "consume"):
            snaps = [r[name].snapshot() for r in runs if name in r]
            if not snaps:
                continue
            n = len(snaps)
            out[name] = {
                "items": sum(s["items"] for s in snaps),
                "throughput_per_sec": round(
                    sum(s["throughput_per_sec"] for s in snaps), 1),
                "busy_s": round(sum(s["busy_s"] for s in snaps), 3),
                "starve_s": round(sum(s["starve_s"] for s in snaps), 3),
                "backpressure_s": round(
                    sum(s["backpressure_s"] for s in snaps), 3),
                "stall_frac": round(
                    sum(s["stall_frac"] for s in snaps) / n, 3),
                "mean_queue_depth": round(
                    sum(s["mean_queue_depth"] for s in snaps) / n, 2),
            }
        return out

    # ---- the pipeline ---------------------------------------------------

    def __call__(self, it: Iterator) -> Iterator:
        import logging
        from bigdl_tpu.dataset.mt_batch import (MTLabeledBGRImgToBatch,
                                                _check_crop_fits,
                                                assemble_batch,
                                                assemble_batch_u8,
                                                crop_flip_host)
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.utils import chaos, file_io
        from bigdl_tpu.utils.random_generator import RandomGenerator

        logger = logging.getLogger("bigdl_tpu")
        stats = {name: StageStats(name)
                 for name in ("read", "decode", "assemble", "consume")}
        self._active_stats.append(stats)
        _LIVE.add(self)
        quarantine = RecordQuarantine(self.max_bad_records)
        self.quarantine = quarantine

        # the caller's stream is CLONED, not handed off: the assembler
        # draws from the clone in record order, and each batch carries the
        # clone's post-draw state — committed to the shared instance only
        # when the consumer takes the batch.  Read-ahead discarded at an
        # epoch rollover never advances the user-visible stream, so the
        # pipelined sequence stays bit-identical to the synchronous path
        # regardless of ring depths or how far ahead the engine ran.
        #
        # Multiple engines on ONE stream (a ShardedDataSet applies the
        # transformer per shard and the driver pulls the shard iterators
        # alternately): only the FIRST active fork is the stream's
        # committer — secondaries draw from a deterministically reseeded
        # fork (decorrelated per-shard augmentation, the reference's
        # per-partition RNG model, ``dataset/DataSet.scala:262``) and
        # never commit, so alternating consumption cannot interleave
        # incoherent positions onto the caller's stream.  Synchronous-path
        # bit-parity is therefore a SINGLE-engine contract; multi-shard
        # runs are run-to-run deterministic instead.
        shared_rng = RandomGenerator.RNG()
        active_forks = shared_rng.__dict__.setdefault("_ingest_forks", set())
        # secondary forks are numbered by how many forks are already
        # active — NOT a global counter, so re-running the same pipeline
        # derives the identical per-shard seeds
        fork_rank = len(active_forks)
        fork_token = object()
        primary = fork_rank == 0
        active_forks.add(fork_token)
        drawer = RandomGenerator(0)
        drawer.np.set_state(shared_rng.np.get_state())
        if not primary:
            # decorrelate the secondary fork: seed from the fork point +
            # the fork rank, so each shard's stream is distinct but every
            # run derives the identical sequence
            mix = int(np.asarray(shared_rng.np.get_state()[1],
                                 np.uint64).sum())
            drawer.set_seed((mix ^ (0x9E3779B1 * fork_rank)) % (2 ** 31))

        stop = threading.Event()

        # host-memory governor accounting: every bounded buffer this run
        # owns (record ring, decode in-flight window, batch ring) keeps a
        # byte ledger current, rolled up into Resources/host_bytes
        rec_acct = _governor.account("ingest_record_ring")
        bat_acct = _governor.account("ingest_batch_ring")
        dec_acct = _governor.account("ingest_decode_window")
        dec_outstanding = [0]    # this run's share, settled at teardown

        def _rec_nbytes(item):
            if isinstance(item, tuple) and len(item) == 2:
                return _item_nbytes(getattr(item[1], "bytes", None))
            return 0

        def _bat_nbytes(item):
            if isinstance(item, tuple) and len(item) == 2:
                return _item_nbytes(item[0])
            return 0

        def _dec_charge(rec, sign: int) -> None:
            n = _item_nbytes(getattr(rec, "bytes", None))
            if n:
                dec_outstanding[0] += sign * n
                (dec_acct.add if sign > 0 else dec_acct.sub)(n)

        record_ring = _Ring(self.record_ring_depth,
                            producer=stats["read"],
                            consumer=stats["assemble"],
                            account=rec_acct, sizer=_rec_nbytes)
        batch_ring = _Ring(self.batch_ring_depth,
                           producer=stats["assemble"],
                           consumer=stats["consume"],
                           account=bat_acct, sizer=_bat_nbytes)
        pool = _DecodePool(self.decode_workers,
                           thread_name_prefix="ingest-decode")
        epoch_cache = self.epoch_cache
        ch, cw = self.crop

        # shared stage state: everything a RESTARTED stage thread needs to
        # resume exactly where its dead predecessor stopped (the chaos
        # injector kills at the loop top, a consistent point) lives here,
        # never in thread-local closure variables
        rd = {"index": 0, "exhausted": False, "inhand": None}
        rd_done = [False]        # orderly exit (completion or surfaced error)
        asm = {"pending": deque(),   # (index, record, decode future) in order
               "done": False,        # upstream exhausted / error queued
               "aborted": False,     # teardown stop observed mid-wait
               "imgs": [], "recs": [], "offsets": [], "flips": [],
               "seeds": [],          # ride-along ColorJitter keys (jitter on)
               "items": 0,           # records fully handled (chaos kill key)
               "decode_restarts": 0}
        asm_done = [False]

        def reader() -> None:
            """Pull upstream records into the record ring.  The upstream
            iterator draws no host RNG (crop/flip belongs to the assembler;
            reshuffles to the training driver's producer), so running it on
            its own thread keeps the single-drawer contract intact."""
            try:
                while True:
                    if chaos.kill_stage_thread("reader", rd["index"]):
                        return          # silent death — supervisor's job
                    chaos.starve_stage("read", rd["index"])
                    t0 = time.monotonic()
                    try:
                        rec = next(it)
                    except StopIteration:
                        rd["exhausted"] = True
                        break
                    idx, rd["index"] = rd["index"], rd["index"] + 1
                    try:
                        # transient blips retry with the file_io backoff;
                        # data faults (fatal) pass straight through
                        file_io.retrying(chaos.on_record_read, idx,
                                         op="ingest record read")
                    except BaseException as e:
                        if _is_data_error(e):
                            quarantine.admit("read", idx,
                                             getattr(rec, "name", None), e)
                            continue     # one record skipped, stream lives
                        raise
                    t1 = time.monotonic()
                    stats["read"].add(items=1, busy_s=t1 - t0)
                    telemetry.add_span_s("ingest/read", t0, t1)
                    if not record_ring.put((idx, rec), stop):
                        # teardown aborted the handoff: keep the in-hand
                        # record so a fallback drain loses nothing
                        rd["inhand"] = (idx, rec)
                        rd_done[0] = True
                        return
                record_ring.put(_END, stop)
                rd_done[0] = True
            except BaseException as e:  # surface downstream
                record_ring.put(e, stop)
                rd_done[0] = True

        def timed_decode(idx: int, rec) -> np.ndarray:
            if chaos.kill_stage_thread("decode", idx):
                raise _StageKilledError(
                    f"decode worker died at record {idx}")
            chaos.starve_stage("decode", idx)
            t0 = time.monotonic()
            chaos.on_decode(idx)
            key = getattr(rec, "name", None)
            img = epoch_cache.get(key) if epoch_cache is not None else None
            if img is None:
                try:
                    img = MTLabeledBGRImgToBatch._decode(rec.bytes)
                except Exception as e:
                    # junk bytes, not junk machinery: quarantinable
                    raise IngestDataError(
                        f"undecodable image at stream position {idx}: "
                        f"{e!r}") from e
                if epoch_cache is not None:
                    epoch_cache.put(key, img)
            t1 = time.monotonic()
            stats["decode"].add(items=1, busy_s=t1 - t0)
            telemetry.add_span_s("ingest/decode", t0, t1)
            return img

        def fill(block: bool) -> None:
            """Top up the in-flight decode window.  Blocking only when
            the window is empty keeps the assembler from stalling on a
            slow upstream while it still has decoded work to pack."""
            pending = asm["pending"]
            # under host-memory pressure the read-ahead pauses: the
            # window collapses to depth 1 (progress, never deadlock)
            window = (1 if _governor.under_pressure()
                      else self.decoded_ring_depth)
            while not asm["done"] and len(pending) < window:
                item = (record_ring.get(stop) if block and not pending
                        else record_ring.try_get())
                if item is _NO_ITEM:
                    if block and not pending:
                        # stop was set mid-get: TEARDOWN, not upstream
                        # completion — the fallback drain must still see
                        # the remaining upstream records
                        asm["aborted"] = True
                    return
                if item is _END:
                    asm["done"] = True
                    return
                if isinstance(item, BaseException):
                    asm["done"] = True
                    pending.append((None, None, item))
                    return
                idx, rec = item
                _dec_charge(rec, +1)
                pending.append((idx, rec,
                                pool.submit(timed_decode, idx, rec)))

        def pack_batch() -> Tuple["MiniBatch", int, float]:
            """The ONE batch-packing path (native assemble + labels)
            over the shared lists — pipelined emit and fallback emit
            both call it, so they can never drift apart.  Returns the
            batch plus (record count, pack seconds); the CALLER accounts
            the stats once the batch is actually handed off — a pack
            discarded by a teardown-aborted ring put (the fallback
            re-packs it) must not be counted twice."""
            imgs, recs = asm["imgs"], asm["recs"]
            t0 = time.monotonic()
            offs = np.asarray(asm["offsets"], np.int32).reshape(len(imgs), 2)
            fl = np.asarray(asm["flips"], np.uint8)
            if self.device_augment:
                # ship FULL uint8 frames + the ride-along draws; the
                # per-pixel crop/flip/transpose belongs to
                # nn.DeviceAugment inside the fused step.  One np.stack
                # memcpy when the batch's source frames share a shape;
                # a mixed-shape batch pre-crops on the declared host
                # fallback (crop_flip_host) and ships identity
                # ride-alongs — same trained weights either way.
                if len({im.shape for im in imgs}) == 1:
                    frames = np.stack(imgs)
                else:
                    frames = crop_flip_host(imgs, self.crop, offs, fl)
                    offs = np.zeros_like(offs)
                    fl = np.zeros_like(fl)
                x = [frames, offs, fl]
                if self.device_jitter:
                    x.append(np.asarray(asm["seeds"], np.int32))
            elif self.device_normalize:
                x = assemble_batch_u8(imgs, self.crop, offs, fl,
                                      n_threads=self.assemble_threads)
            else:
                x = assemble_batch(imgs, self.crop, offs, fl,
                                   self.mean, self.std,
                                   n_threads=self.assemble_threads)
            y = np.asarray([r.label for r in recs], np.float32)
            t1 = time.monotonic()
            # the span records the pack that really happened (a second
            # pack after an aborted handoff is a real event on the
            # timeline); the STATS are the caller's, on handoff only
            telemetry.add_span_s("ingest/assemble", t0, t1,
                                 {"batch": len(imgs)})
            return MiniBatch(x, y), len(imgs), t1 - t0

        def admit_and_append(idx: int, rec, img) -> bool:
            """Crop-fit check (quarantinable), crop/flip draws in strict
            record order — the same draw sequence MTLabeledBGRImgToBatch
            makes — and append to the shared batch lists.  False when
            the record was quarantined (no RNG drawn: the surviving
            stream's draws equal the sync path's over the survivors).
            Shared by the assembler thread and the fallback drain."""
            try:
                _check_crop_fits(
                    [img], self.crop,
                    describe=lambda _i: (
                        f"StreamingIngest: record {len(asm['imgs'])} of "
                        f"the current batch (label {rec.label})"))
            except ValueError as e:
                quarantine.admit("assemble", idx, rec.name, e)
                return False
            h, w = img.shape[:2]
            if self.random_crop:
                oy = drawer.random_int(0, h - ch + 1)
                ox = drawer.random_int(0, w - cw + 1)
            else:
                oy, ox = (h - ch) // 2, (w - cw) // 2
            fl = int(drawer.uniform() < 0.5) if self.hflip else 0
            asm["imgs"].append(img if img.ndim == 3 else img[:, :, None])
            asm["recs"].append(rec)
            asm["offsets"].append((oy, ox))
            asm["flips"].append(fl)
            if self.device_jitter:
                # the per-record ColorJitter key rides the same clone-
                # and-commit stream: an extra draw AFTER crop/flip, so
                # it is replay-deterministic (and intentionally not
                # host-path-parity — the host path has no jitter)
                asm["seeds"].append(drawer.random_int(0, 2 ** 31 - 1))
            return True

        def emit() -> bool:
            batch, n, pack_s = pack_batch()
            # depth-1 escalation: one batch larger than the whole host
            # budget cannot be backpressured away — structured error
            _governor.check_item("ingest_batch_ring", _item_nbytes(batch))
            ok = batch_ring.put((batch, drawer.np.get_state()), stop)
            if ok:
                stats["assemble"].add(items=n, busy_s=pack_s)
                # on a teardown-aborted put the DRAWN batch stays in the
                # shared lists: the fallback drain re-emits it with its
                # already-drawn offsets/flips instead of losing it
                for key in ("imgs", "recs", "offsets", "flips", "seeds"):
                    asm[key].clear()
            return ok

        def assembler() -> None:
            pending = asm["pending"]
            imgs = asm["imgs"]
            try:
                while True:
                    if chaos.kill_stage_thread("assembler", asm["items"]):
                        return          # silent death — supervisor's job
                    chaos.starve_stage("assemble", asm["items"])
                    fill(block=True)
                    if asm["aborted"]:
                        asm_done[0] = True   # orderly teardown exit
                        return
                    if not pending:
                        break
                    idx, rec, fut = pending.popleft()
                    if rec is None:      # upstream error, in order
                        raise fut
                    _dec_charge(rec, -1)
                    try:
                        if fut.done():
                            img = fut.result()
                        else:            # wait-on-decode = assemble starve
                            t0 = time.monotonic()
                            img = fut.result()
                            stats["assemble"].add(
                                starve_s=time.monotonic() - t0)
                    except _StageKilledError as e:
                        # a dead decode WORKER is infrastructure: the
                        # record's bytes are fine — resubmit the decode,
                        # bounded like any stage restart
                        if asm["decode_restarts"] >= self.max_stage_restarts:
                            raise IngestInfraError(
                                "ingest decode worker died and the "
                                "restart budget (bigdl.ingest."
                                f"maxStageRestarts={self.max_stage_restarts}"
                                ") is exhausted",
                                diagnosis=self.stats()) from e
                        asm["decode_restarts"] += 1
                        sup.count_restart("decode")
                        logger.warning(
                            "ingest decode worker died on record %d — "
                            "resubmitting (%d/%d)", idx,
                            asm["decode_restarts"], self.max_stage_restarts)
                        _dec_charge(rec, +1)
                        pending.appendleft(
                            (idx, rec, pool.submit(timed_decode, idx, rec)))
                        continue
                    except BaseException as e:
                        if _is_data_error(e):
                            # skipped BEFORE any RNG draw: the surviving
                            # stream's draw sequence equals the sync
                            # path's over the surviving records
                            quarantine.admit("decode", idx, rec.name, e)
                            asm["items"] += 1
                            continue
                        raise
                    fill(block=False)    # decode of the NEXT batch proceeds
                    appended = admit_and_append(idx, rec, img)
                    asm["items"] += 1
                    if not appended:
                        continue
                    if len(imgs) == self.batch_size:
                        if not emit():
                            asm_done[0] = True
                            return
                if imgs:
                    if not emit():
                        asm_done[0] = True
                        return
                batch_ring.put(_END, stop)
                asm_done[0] = True
            except BaseException as e:  # surface at the consumer
                batch_ring.put(e, stop)
                asm_done[0] = True

        def _thread_factory(fn, tname):
            def factory():
                t = threading.Thread(target=fn, daemon=True, name=tname)
                t.start()
                return t
            return factory

        autoscale_tick = None
        if self.autoscale:
            cores = max(1, os.cpu_count() or 1)
            as_max = config.get_int("bigdl.ingest.autoscale.max", 0) or cores
            policy = AutoscalePolicy(
                min_workers=config.get_int("bigdl.ingest.autoscale.min", 1),
                max_workers=as_max,
                up_starve_frac=config.get_float(
                    "bigdl.ingest.autoscale.upStarveFrac", 0.2),
                down_starve_frac=config.get_float(
                    "bigdl.ingest.autoscale.downStarveFrac", 0.02),
                patience=config.get_int("bigdl.ingest.autoscale.patience",
                                        2),
                cooldown=config.get_int("bigdl.ingest.autoscale.cooldown",
                                        3))
            prev = {"starve": 0.0, "backpressure": 0.0,
                    "t": time.monotonic()}

            def autoscale_tick() -> None:
                """One supervisor-cadence decision: per-interval deltas
                of the assemble stage's stall counters become fractions
                of the interval, the pure policy decides, the pool (and
                the native assembler's thread count, in tandem) acts."""
                starve, bp = stats["assemble"].stall_seconds()
                now = time.monotonic()
                dt = max(now - prev["t"], 1e-9)
                starve_frac = (starve - prev["starve"]) / dt
                bp_frac = (bp - prev["backpressure"]) / dt
                prev.update(starve=starve, backpressure=bp, t=now)
                delta = policy.decide(starve_frac, bp_frac, pool.workers,
                                      _governor.under_pressure())
                if not delta:
                    return
                n = pool.set_workers(pool.workers + delta)
                self.assemble_threads = n
                self.stage_workers["decode"] = n
                self.stage_workers["assemble"] = n
                direction = "up" if delta > 0 else "down"
                self.autoscale_events[direction] += 1
                telemetry.counter(
                    f"Ingest/autoscale_{direction}",
                    labels={"stage": "decode"}, summary=True,
                    help="ingest worker-scaling actions taken by the "
                         "stage autoscaler").inc()
                logger.info(
                    "ingest '%s' autoscale %s: decode/assemble workers "
                    "-> %d (starve %.2f, backpressure %.2f of interval)",
                    self.name, direction, n, starve_frac, bp_frac)

        sup = _StageSupervisor(self.max_stage_restarts, self.stall_timeout,
                               diagnose=self.stats,
                               rings=[record_ring, batch_ring],
                               run_stats=stats,
                               autoscale=autoscale_tick,
                               autoscale_interval=config.get_float(
                                   "bigdl.ingest.autoscale.intervalSec",
                                   0.25))
        self.supervisor = sup
        sup.register("reader", _thread_factory(reader, "ingest-reader"),
                     rd_done)
        sup.register("assembler",
                     _thread_factory(assembler, "ingest-assembler"),
                     asm_done)
        sup.start()
        fault_pair = (quarantine, sup)
        self._active_faults.append(fault_pair)

        # run-scoped shrinker: when the governor detects host-memory
        # pressure it halves this run's ring depths and decode window —
        # the existing backpressure machinery does the rest.  Shrinks
        # persist for the engine's lifetime (self.decoded_ring_depth).
        shrink_key = f"ingest:{self.name}:{id(stop)}"

        def _shrink() -> None:
            rl = record_ring.shrink()
            bl = batch_ring.shrink()
            self.decoded_ring_depth = max(
                1, int(self.decoded_ring_depth) // 2)
            logger.warning(
                "host-memory pressure: ingest '%s' ring depths shrink to "
                "record=%d batch=%d decode-window=%d", self.name, rl, bl,
                self.decoded_ring_depth)

        _governor.register_shrinker(shrink_key, _shrink)

        def _sync_record_source() -> Iterator:
            """Leftover + remaining records for the fallback drain, in
            exact stream order: the assembler's in-flight window, then
            the record ring, then the (single-threaded, chaos-gated)
            remainder of the upstream iterator."""
            upstream_done = asm["done"] or rd["exhausted"]
            upstream_err = None
            for idx, rec, _fut in asm["pending"]:
                if rec is None:
                    upstream_err = _fut
                    upstream_done = True
                    break
                _dec_charge(rec, -1)
                yield idx, rec
            asm["pending"].clear()
            while upstream_err is None:
                item = record_ring.try_get()
                if item is _NO_ITEM:
                    break
                if item is _END:
                    upstream_done = True
                    break
                if isinstance(item, BaseException):
                    upstream_err = item
                    break
                yield item
            if upstream_err is None and rd["inhand"] is not None:
                # the record the reader held when teardown aborted its
                # ring put — after everything it already handed off
                yield rd["inhand"]
                rd["inhand"] = None
            while upstream_err is None and not upstream_done:
                try:
                    rec = next(it)
                except StopIteration:
                    break
                idx, rd["index"] = rd["index"], rd["index"] + 1
                try:
                    file_io.retrying(chaos.on_record_read, idx,
                                     op="ingest record read")
                except BaseException as e:
                    if _is_data_error(e):
                        quarantine.admit("read", idx,
                                         getattr(rec, "name", None), e)
                        continue
                    raise
                stats["read"].add(items=1)
                yield idx, rec
            if upstream_err is not None:
                raise upstream_err

        def _fallback_tail(err: BaseException) -> Iterator:
            """Finish the epoch on the synchronous path: same drawer RNG,
            same quarantine, no stage threads — the batch stream
            continues bit-identically to an uninterrupted run (modulo
            quarantined records).  Only safe once every stage thread is
            verifiably dead (a live reader still owns the upstream
            iterator); otherwise the original failure re-raises."""
            self.fallbacks += 1
            telemetry.counter(
                "Ingest/fallbacks", summary=True,
                help="mid-epoch switches to the synchronous ingest path"
            ).inc()
            logger.warning(
                "ingest engine '%s' declared dead (%s) — falling back to "
                "the synchronous path mid-epoch; per-stage stats: %s",
                self.name, err, self.stats())
            sup.stop()
            stop.set()
            for tname in ("reader", "assembler"):
                sup.thread(tname).join(timeout=5)
            if any(sup.thread(n).is_alive()
                   for n in ("reader", "assembler")):
                logger.error(
                    "ingest fallback impossible: a stage thread is still "
                    "alive and owns the upstream iterator")
                raise err
            # completed batches already in the ring are valid drawn work:
            # deliver them (committing their RNG positions) before
            # continuing from the first unassembled record
            while True:
                item = batch_ring.try_get()
                if item is _NO_ITEM or item is _END:
                    break
                if isinstance(item, BaseException):
                    raise item
                batch, rng_state = item
                if primary:
                    shared_rng.np.set_state(rng_state)
                stats["consume"].add(items=1)
                yield batch

            def emit_sync():
                # same pack as the pipelined emit(); consumption == the
                # yield itself, so the RNG position commits here
                batch, n, pack_s = pack_batch()
                stats["assemble"].add(items=n, busy_s=pack_s)
                for key in ("imgs", "recs", "offsets", "flips", "seeds"):
                    asm[key].clear()
                if primary:
                    shared_rng.np.set_state(drawer.np.get_state())
                stats["consume"].add(items=1)
                return batch

            if len(asm["imgs"]) >= self.batch_size:
                # a fully-drawn batch whose ring put was aborted by the
                # teardown: emit it before touching new records
                yield emit_sync()

            for idx, rec in _sync_record_source():
                try:
                    img = timed_decode(idx, rec)
                except BaseException as e:
                    if _is_data_error(e):
                        quarantine.admit("decode", idx, rec.name, e)
                        continue
                    raise
                if not admit_and_append(idx, rec, img):
                    continue
                if len(asm["imgs"]) == self.batch_size:
                    yield emit_sync()
            if asm["imgs"]:
                yield emit_sync()

        try:
            while True:
                # governor tick from the consumer side too: serving-only
                # processes have no optimizer loop to poll for them
                _governor.poll()
                # blocked time inside get() is charged to consume.starve_s
                # by the ring itself; the failure event doubles as the
                # stop so a supervisor escalation wakes this wait at once
                sup.consumer_waiting_since = time.monotonic()
                item = batch_ring.get(sup.failed)
                sup.consumer_waiting_since = None
                if item is _NO_ITEM:
                    # the supervisor declared the engine dead
                    err = sup.failure or IngestInfraError(
                        "ingest engine failed", diagnosis=self.stats())
                    telemetry.counter(
                        "Ingest/engine_failures", summary=True,
                        help="ingest engines declared dead by the "
                             "supervisor").inc()
                    if self.fallback_on_failure:
                        yield from _fallback_tail(err)
                        return
                    logger.error(
                        "ingest engine '%s' declared dead: %s; per-stage "
                        "stats: %s", self.name, err, self.stats())
                    raise err
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                batch, rng_state = item
                if primary:
                    # commit the drawn-through position: the caller's
                    # stream advances exactly as far as the batches it
                    # actually took
                    shared_rng.np.set_state(rng_state)
                stats["consume"].add(items=1)
                yield batch
        finally:
            _governor.unregister_shrinker(shrink_key)
            active_forks.discard(fork_token)
            for i, run in enumerate(self._active_stats):
                if run is stats:
                    del self._active_stats[i]
                    break
            self._last_stats = stats
            for i, pair in enumerate(self._active_faults):
                if pair is fault_pair:
                    del self._active_faults[i]
                    break
            self._last_faults = fault_pair
            self.run_history.append({
                "quarantine": quarantine.summary(),
                "stage_restarts": sup.restarts})
            sup.stop()          # no restarts while tearing down
            stop.set()
            # cancel queued decodes so teardown never waits on work whose
            # output nobody will read (mirrors the MT transformer fix)
            pool.shutdown(wait=False, cancel_futures=True)
            for ring in (record_ring, batch_ring):
                ring.drain()
            # a declared-dead engine's threads are dead or wedged beyond
            # recovery (that is WHY it was declared dead): don't spend
            # the full grace join on a thread that provably won't exit
            grace = 0.5 if sup.failure is not None else 5
            sup.thread("reader").join(timeout=grace)
            sup.thread("assembler").join(timeout=grace)
            # a final put can land between the first drain and the join —
            # drain again so no full batch stays pinned in the ring
            for ring in (record_ring, batch_ring):
                ring.drain()
            # settle this run's decode-window share: the account is
            # process-global (shared by concurrent engines), so only the
            # bytes THIS run still holds get released
            if dec_outstanding[0] > 0:
                dec_acct.sub(dec_outstanding[0])
            elif dec_outstanding[0] < 0:
                dec_acct.add(-dec_outstanding[0])
            dec_outstanding[0] = 0


def summary_scalars():
    """(tag, value) pairs for the training summary: per-stage throughput,
    stall fraction, and ring occupancy of every engine with an ACTIVE run
    (idle engines from finished pipelines are excluded — their stale final
    counters must not pollute a later run's series).  Tags always include
    the engine's ``name`` so the series stays stable when a second engine
    (a validation pipeline) goes live mid-run."""
    out = []
    for eng in sorted((e for e in _LIVE if e.has_active_run()),
                      key=lambda e: e.name):
        prefix = f"Ingest/{eng.name}"
        for stage, snap in eng.stats().items():
            out.append((f"{prefix}/{stage}/throughput",
                        snap["throughput_per_sec"]))
            out.append((f"{prefix}/{stage}/stall_frac", snap["stall_frac"]))
            if snap["mean_queue_depth"]:
                out.append((f"{prefix}/{stage}/queue_depth",
                            snap["mean_queue_depth"]))
        # per-stage worker gauges + autoscale action counters (ISSUE 16:
        # the driver decomposition log and charts must show what the
        # autoscaler actually did, not just its throughput effect)
        for stage, n in eng.stage_workers.items():
            out.append((f"{prefix}/{stage}/workers", n))
        for direction, n in eng.autoscale_events.items():
            if n:
                out.append((f"{prefix}/autoscale_{direction}", n))
        if eng.epoch_cache is not None:
            cache = eng.epoch_cache.stats()
            out.append((f"{prefix}/epoch_cache_hits", cache["hits"]))
            out.append((f"{prefix}/epoch_cache_misses", cache["misses"]))
        # self-healing series surface only once they are nonzero: a
        # clean run's charts stay exactly as before.  Summed over every
        # ACTIVE run — a multi-shard pipeline must not report just the
        # last-started shard's counters
        quarantined = eng.quarantined_count()
        if quarantined:
            out.append((f"{prefix}/quarantined", quarantined))
        restarts = eng.stage_restart_count()
        if restarts:
            out.append((f"{prefix}/stage_restarts", restarts))
    return out


def _stall_diagnostics() -> dict:
    """Per-engine stats + ring ages for the hung-step watchdog: when a
    driver stall traces back to a wedged data pipeline, the fire log
    names the stage instead of just the symptom."""
    return {eng.name: {"stats": eng.stats(), "faults": eng.fault_stats()}
            for eng in sorted(_LIVE, key=lambda e: e.name)
            if eng.has_active_run()}


# the engine's scalars flow through the telemetry registry's single flush
# path: the driver's one emission loop pulls this provider instead of
# special-casing the ingest module (tags unchanged — Ingest/<name>/...)
telemetry.REGISTRY.register_provider("ingest", summary_scalars)

# the hung-step watchdog reports these with every fire: "the step hung"
# arrives with "which ring is stale and which stage died" attached
from bigdl_tpu.utils import elastic as _elastic  # noqa: E402

_elastic.register_stall_diagnostic("ingest", _stall_diagnostics)
