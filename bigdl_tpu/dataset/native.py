"""ctypes bindings for the native runtime library (``native/``).

Reference equivalent: the role of the ``bigdl-core`` MKL-JNI submodule —
native code for the CPU-side hot paths.  On TPU the numeric hot path is
XLA's, so the native layer covers what still runs on host CPUs: SequenceFile
IO and multi-threaded batch assembly.

The library is built on demand with ``make`` (g++); every entry point has a
pure-Python fallback so the framework works without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

from bigdl_tpu import analysis

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libbigdl_native.so")

_lock = analysis.make_lock("native.build")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def load_native() -> Optional[ctypes.CDLL]:
    """The shared library, building it on first use; None when unavailable
    (no sources, no compiler, ...)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.path.isdir(_NATIVE_DIR):
            # always invoke make: it is a no-op when the .so is fresh and
            # rebuilds when the C++ sources changed (stale-binary guard)
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                               capture_output=True, timeout=120)
            except Exception:
                pass  # fall through: a previously-built .so may still load
        if not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.seqfile_open.restype = ctypes.c_void_p
        lib.seqfile_open.argtypes = [ctypes.c_char_p]
        lib.seqfile_next.restype = ctypes.c_int
        lib.seqfile_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int)]
        lib.seqfile_close.argtypes = [ctypes.c_void_p]
        lib.seqfile_create.restype = ctypes.c_void_p
        lib.seqfile_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                       ctypes.c_char_p, ctypes.c_char_p]
        lib.seqfile_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int, ctypes.c_char_p,
                                       ctypes.c_int]
        lib.seqfile_close_writer.argtypes = [ctypes.c_void_p]
        lib.assemble_batch.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),           # images
            ctypes.POINTER(ctypes.c_int),              # heights
            ctypes.POINTER(ctypes.c_int),              # widths
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),              # offsets
            ctypes.POINTER(ctypes.c_ubyte),            # flips
            ctypes.POINTER(ctypes.c_float),            # mean
            ctypes.POINTER(ctypes.c_float),            # std
            ctypes.POINTER(ctypes.c_float),            # out
            ctypes.c_int]                              # n_threads
        # raw-uint8 crop/flip/pack (device-normalize ingest layout);
        # guarded: a stale pre-r4 .so may lack the symbol
        if hasattr(lib, "assemble_batch_u8"):
            lib.assemble_batch_u8.argtypes = [
                ctypes.POINTER(ctypes.c_void_p),       # images
                ctypes.POINTER(ctypes.c_int),          # heights
                ctypes.POINTER(ctypes.c_int),          # widths
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),          # offsets
                ctypes.POINTER(ctypes.c_ubyte),        # flips
                ctypes.POINTER(ctypes.c_ubyte),        # out
                ctypes.c_int]                          # n_threads
        _lib = lib
        return _lib


def native_available() -> bool:
    return load_native() is not None


#: every entry point the framework dispatches to; a .so missing one of
#: these is a stale pre-r4 build that silently degrades the ingest path
REQUIRED_SYMBOLS = ("seqfile_open", "seqfile_next", "seqfile_close",
                    "seqfile_create", "seqfile_append",
                    "seqfile_close_writer", "assemble_batch",
                    "assemble_batch_u8")


def check_build() -> "ctypes.CDLL":
    """CI-facing STRICT build: run ``make -C native`` surfacing compiler
    errors, load the library, and verify every required symbol — the
    checked counterpart of :func:`load_native`'s permissive "fall back to
    numpy" behaviour.  A toolchain-equipped environment that silently
    benchmarks the numpy fallback (because the build broke or an old .so
    lacks ``assemble_batch_u8``) reports numbers that are off by an order
    of magnitude; this fails loudly instead."""
    global _lib, _tried
    try:
        proc = subprocess.run(["make", "-C", _NATIVE_DIR], check=False,
                              capture_output=True, timeout=300, text=True)
    except FileNotFoundError as e:
        raise RuntimeError(f"native build failed: make not found ({e})")
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed (make -C {_NATIVE_DIR} exited "
            f"{proc.returncode}):\n{proc.stderr[-2000:]}")
    with _lock:
        # force a reload: a permissive load_native() earlier in the
        # process may have cached a stale (or absent) library
        _lib, _tried = None, False
    lib = load_native()
    if lib is None:
        raise RuntimeError(
            f"native build succeeded but {_LIB_PATH} failed to load")
    missing = [s for s in REQUIRED_SYMBOLS if not hasattr(lib, s)]
    if missing:
        raise RuntimeError(
            f"native library {_LIB_PATH} is missing symbols {missing} — "
            "stale build? `make -C native clean` and rebuild; the numpy "
            "fallback would silently mis-measure the ingest path")
    return lib
