"""Text pipeline: tokenization, dictionary, sentence→sample transforms.

Reference equivalent: ``dataset/text/`` (8 files) — ``SentenceTokenizer`` /
``SentenceSplitter`` (OpenNLP there; regex here — no JVM), ``Dictionary``,
``TextToLabeledSentence``, ``LabeledSentenceToSample``, padding.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer


class SentenceSplitter(Transformer):
    """Paragraph → sentences (reference ``SentenceSplitter``; regex-based)."""

    _pat = re.compile(r"(?<=[.!?])\s+")

    def __call__(self, it: Iterator[str]) -> Iterator[str]:
        for text in it:
            for s in self._pat.split(text):
                s = s.strip()
                if s:
                    yield s


class SentenceTokenizer(Transformer):
    """Sentence → token list (reference ``SentenceTokenizer``)."""

    _pat = re.compile(r"[A-Za-z0-9']+|[.,!?;:()\"]")

    def __call__(self, it: Iterator[str]) -> Iterator[List[str]]:
        for s in it:
            yield self._pat.findall(s.lower())


class SentenceBiPadding(Transformer):
    """Wrap each sentence in start/end markers (reference
    ``pyspark/bigdl/dataset/sentence.py`` sentences_bipadding — the rnn
    example's LM pipeline marks sentence boundaries with these tokens)."""

    START = "SENTENCESTART"
    END = "SENTENCEEND"

    def __call__(self, it: Iterator[str]) -> Iterator[str]:
        for s in it:
            yield f"{self.START} {s} {self.END}"


class Dictionary:
    """Word ↔ index vocabulary (reference ``dataset/text/Dictionary.scala``).

    Indices are 0-based; ``vocab_size`` caps to the most frequent words, the
    rest map to an out-of-vocabulary index = len(vocab) (as the reference's
    discard-and-UNK behavior).
    """

    def __init__(self, sentences: Optional[Iterable[List[str]]] = None,
                 vocab_size: Optional[int] = None):
        self.word2index: Dict[str, int] = {}
        self.index2word: Dict[int, str] = {}
        if sentences is not None:
            counts = Counter(w for s in sentences for w in s)
            ordered = [w for w, _ in counts.most_common(vocab_size)]
            for i, w in enumerate(ordered):
                self.word2index[w] = i
                self.index2word[i] = w

    def vocab_size(self) -> int:
        return len(self.word2index)

    def get_index(self, word: str) -> int:
        return self.word2index.get(word, len(self.word2index))

    def get_word(self, index: int) -> str:
        return self.index2word.get(index, "<unk>")

    def add_word(self, word: str) -> int:
        if word not in self.word2index:
            i = len(self.word2index)
            self.word2index[word] = i
            self.index2word[i] = word
        return self.word2index[word]


class LabeledSentence:
    """Token-index sequence + per-step or scalar label
    (reference ``LabeledSentence``)."""

    __slots__ = ("data", "label")

    def __init__(self, data: Sequence[int], label):
        self.data = np.asarray(data, dtype=np.float32)
        self.label = np.asarray(label, dtype=np.float32)


class TextToLabeledSentence(Transformer):
    """Token lists → language-model pairs: data=w[0..n-2], label=w[1..n-1]
    (reference ``TextToLabeledSentence``)."""

    def __init__(self, dictionary: Dictionary):
        self.dictionary = dictionary

    def __call__(self, it: Iterator[List[str]]) -> Iterator[LabeledSentence]:
        for tokens in it:
            idx = [self.dictionary.get_index(w) for w in tokens]
            if len(idx) < 2:
                continue
            yield LabeledSentence(idx[:-1], idx[1:])


class LabeledSentenceToSample(Transformer):
    """LabeledSentence → Sample, optionally one-hot and/or fixed-length padded
    (reference ``LabeledSentenceToSample``).

    ``one_hot``: emit (T, vocab) one-hot features like the reference's SimpleRNN
    pipeline; else raw index vectors (for LookupTable embedding, 1-based labels
    for ClassNLL as in the reference: label = index + 1).

    Out-of-vocabulary indices (``Dictionary.get_index`` returns
    ``vocab_size()`` for unknown words) are clamped into the last slot
    ``vocab_length - 1``, so pass ``vocab_length = dictionary.vocab_size() + 1``
    to give OOV its own column, or ``vocab_size()`` to fold it onto the rarest
    word.
    """

    def __init__(self, vocab_length: int, fixed_length: Optional[int] = None,
                 one_hot: bool = True):
        self.vocab_length = vocab_length
        self.fixed_length = fixed_length
        self.one_hot = one_hot

    def __call__(self, it: Iterator[LabeledSentence]) -> Iterator[Sample]:
        for s in it:
            n = len(s.data)
            t = self.fixed_length or n
            data_idx = np.zeros(t, dtype=np.int32)
            data_idx[:min(n, t)] = np.minimum(
                s.data[:t].astype(np.int32), self.vocab_length - 1)
            label = np.zeros(t, dtype=np.float32)
            m = min(len(s.label), t)
            label[:m] = np.minimum(s.label[:m],
                                   self.vocab_length - 1) + 1.0  # 1-based
            if self.one_hot:
                feat = np.zeros((t, self.vocab_length), dtype=np.float32)
                feat[np.arange(min(n, t)), data_idx[:min(n, t)]] = 1.0
            else:
                feat = data_idx.astype(np.float32) + 1.0  # 1-based for LookupTable
            yield Sample(feat, label)
