"""Evaluator / Validator: metric evaluation over a dataset.

Reference equivalents: ``optim/Evaluator.scala:37-74`` (broadcast model,
mapPartitions forward, metric reduce) and ``optim/Validator.scala`` /
``DistriValidator.scala:35``.

Here: a jitted eval-mode forward per batch; metric accumulation on host with
the reference's mergeable-result algebra.  The distributed trainer reuses
``evaluate_dataset`` per shard and merges results — same reduce shape as the
reference's ``.reduce(metric +)``.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.analysis.hostsync import host_pull
from bigdl_tpu.engine import DispatchPipeline
from bigdl_tpu.engine import to_device as _to_device
from bigdl_tpu.utils import compile_cache
from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import MiniBatch, Sample
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.validation_method import (ValidationMethod,
                                               ValidationResult)


def _eval_forward(model: Module, mesh=None, host_params: bool = False):
    """Eval-mode forward through the tracked compile cache, memoized on
    the model so repeated validation triggers / predict calls reuse one
    compilation (params/state enter as arguments — value changes don't
    retrace; with ``bigdl.compile.cacheDir`` armed a second process
    warm-loads the executable instead of compiling).

    With a ``mesh`` the outputs are pinned replicated: the batch shards
    over the ``data`` axis, and under multi-host training the raw sharded
    logits would span devices this process cannot address — metric code on
    the host could not read them.  Replicated outputs (one all-gather XLA
    schedules with the forward) are host-readable on every process, so all
    processes compute identical validation scores (the reference reduces
    metrics to the driver the same way, ``optim/Evaluator.scala:37-74``).

    Shape bucketing (``bigdl.compile.buckets``): the ``inputs`` argument
    is flagged as the batch-bucketed one, so the first compile of a new
    signature family AOT-precompiles every configured bucket variant and
    registers it with a retrace sentinel — the PR 4 strict sentinel then
    proves a ragged validation run retains zero post-warmup retraces."""
    cache = getattr(model, "_eval_jit", None)
    if cache is None:
        cache = model._eval_jit = {}
    fn = cache.get(id(mesh))
    if fn is None:
        def fwd(params, mstate, inputs):
            out, _ = model.apply(params, inputs, mstate, training=False,
                                 rng=None)
            return out
        from bigdl_tpu.analysis import program_contracts
        from bigdl_tpu.utils import elastic
        topology = elastic.describe_topology(mesh, step="eval")
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            fn = compile_cache.tracked_jit(
                fwd, label="eval_sharded", topology=topology,
                contract=program_contracts.eval_contract(sharded=True),
                bucket_argnums=(2,),
                out_shardings=NamedSharding(mesh, P()))
        else:
            fn = compile_cache.tracked_jit(
                fwd, label="eval", topology=topology,
                contract=program_contracts.eval_contract(sharded=False),
                bucket_argnums=(2,))
        if compile_cache.configured_buckets():
            # the retrace gate: bucket variants registered as warmup
            # compiles by the AOT precompile, any OTHER post-warmup
            # signature — a shape that escaped the bucket plan — is a
            # retrace event (strict raises, warn logs + counts)
            from bigdl_tpu.analysis.retrace import RetraceSentinel
            sentinel = RetraceSentinel.from_config(
                f"eval[{'sharded' if mesh is not None else 'local'}]")
            if sentinel is not None:
                fn.register_sentinel(sentinel)
                fn = sentinel.wrap(fn)
        cache[id(mesh)] = fn
    params, mstate = model.params, model.state
    if host_params:
        # detach params/state from their (possibly global, multi-host)
        # placement: host numpy re-places on this process's local devices,
        # so the un-pinned fn never mixes local inputs with global arrays
        params = jax.tree_util.tree_map(np.asarray, params)
        mstate = jax.tree_util.tree_map(np.asarray, mstate)
    return lambda inputs: fn(params, mstate, inputs)


def evaluate_dataset(model: Module, dataset,
                     methods: Sequence[ValidationMethod],
                     mesh=None
                     ) -> List[Tuple[ValidationMethod, ValidationResult]]:
    """Run ``methods`` over an eval dataset (MiniBatch stream or Sample
    stream + batching applied by the caller).

    ``mesh``: shard each batch over the mesh's ``data`` axis so the forward
    runs data-parallel across devices (the reference evaluates inside the
    cluster, ``optim/Evaluator.scala:37-74``; here XLA's SPMD partitioner
    owns the split).  Batches not divisible by the axis size fall back to
    single-device execution.

    Distributed evaluation (the reference's ``DistriValidator.scala:35``):
    a multi-host :class:`ShardedDataSet` holds only this process's
    partitions, so each process evaluates its LOCAL records with a local
    forward and the mergeable partial results are summed across processes
    — every process returns the identical global metrics.  (The
    mesh-sharded path must NOT be used there: it assumes every process
    feeds the same global batch, which is false for per-process shards.)"""
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    was_training = model.train_mode
    model.evaluate()
    if (isinstance(dataset, ShardedDataSet) and
            getattr(dataset, "dropped_records", 0)):
        # equal-size sharding (static shapes for XLA) truncated the tail;
        # fine for training epochs, but an EVALUATION silently scoring
        # fewer records than the user handed in deserves a warning —
        # a record count divisible by partition_num evaluates everything
        import logging
        logging.getLogger("bigdl_tpu").warning(
            "evaluating a ShardedDataSet that dropped %d tail record(s) "
            "to equalize %d partitions — metrics cover %d records",
            dataset.dropped_records, dataset.partition_num, dataset.size())
    distributed_partials = (isinstance(dataset, ShardedDataSet) and
                            jax.process_count() > 1)
    if distributed_partials:
        mesh = None
    batch_sharding = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        batch_sharding = NamedSharding(mesh, P("data"))
        axis_size = mesh.shape["data"]
    try:
        # LOCAL forward (no mesh pinning), built lazily: serves the
        # whole-batch path when no mesh is given (incl. the multi-host
        # partials branch, where params detach to host — a globally-placed
        # replicated tree cannot mix with process-local batches) AND the
        # fallback for batches not divisible by the data axis, where every
        # process holds the full batch so scores stay identical.  Lazy so
        # mesh runs with divisible-only batches never pay the params
        # fetch; built at most ONCE per call.
        _fallback = {}

        def fwd_local(x):
            if "fn" not in _fallback:
                _fallback["fn"] = _eval_forward(
                    model, host_params=jax.process_count() > 1)
            return _fallback["fn"](x)

        # the mesh-pinned forward exists only when a mesh path can run —
        # building it otherwise would eagerly fetch params for nothing
        fwd = (_eval_forward(model, mesh) if mesh is not None
               else fwd_local)
        totals: List[ValidationResult] = [None] * len(methods)
        it = dataset.data(train=False) if isinstance(
            dataset, AbstractDataSet) else iter(dataset)
        # same dispatch pipeline as the training driver: keep batches in
        # flight with async device→host copies so each batch doesn't pay
        # a full device round-trip (bigdl.pipeline.depth, default 8)
        def drain(item, _nxt):
            out_dev, tgt, true_n = item
            # ONE explicit device_get per validation step: every metric
            # then works on host arrays — N methods cost one pull, not N
            # implicit ones (and none per method inside apply)
            out = host_pull(out_dev, what="validation outputs")
            # bucketed batches were padded going in; the padded rows are
            # sliced off HERE, host-side, so metrics score exactly the
            # true records (bit-identical to an unpadded forward)
            out = compile_cache.slice_rows(out, true_n)
            for i, m in enumerate(methods):
                r = m.apply(out, tgt)
                totals[i] = r if totals[i] is None else totals[i] + r

        buckets = compile_cache.configured_buckets()
        pipeline = DispatchPipeline(drain)
        for batch in it:
            n = batch.size()
            inputs = batch.get_input()
            eff = n
            if buckets:
                # shape bucketing: ragged batches (the validation
                # remainder) pad up to a configured bucket so the
                # forward hits a pre-compiled signature instead of
                # retracing — the choke point the ISSUE names
                eff = compile_cache.bucket_size(n, buckets)
                if eff != n:
                    inputs = compile_cache.pad_batch(inputs, n, eff)
            if batch_sharding is not None and eff % axis_size == 0:
                inputs = jax.tree_util.tree_map(
                    lambda x: jax.device_put(np.asarray(x), batch_sharding),
                    inputs)
                out = fwd(inputs)
            else:
                out = fwd_local(_to_device(inputs))
            pipeline.push(out, batch.get_target(), n)
        pipeline.flush()
        if distributed_partials:
            totals = _merge_partials_across_processes(methods, totals)
        if methods and all(t is None for t in totals):
            # zero batches (globally, in the distributed case — the
            # merge leaves every slot None only when no process saw a
            # record, so all processes raise together): a metric over
            # nothing is a silent lie, not a score.  Raise a CLEAR error
            # instead of returning [] for callers to trip over later.
            raise ValueError(
                "evaluate_dataset got an empty dataset: no batches to "
                "score — feed at least one record, or skip validation "
                "for this trigger")
        return [(m, t) for m, t in zip(methods, totals) if t is not None]
    finally:
        if was_training:
            model.training()


def _merge_partials_across_processes(methods, totals):
    """Sum per-process partial ValidationResults into the global metrics
    (the reference's ``.reduce(metric +)`` across executors).  Collective:
    every process must call with the same method list — the trainers'
    config-symmetry guard enforces that for the validation trigger path."""
    from bigdl_tpu.engine import allgather_sum

    local = [[t.result, t.count] if t is not None else [0.0, 0.0]
             for t in totals]
    summed = allgather_sum(local)
    merged = []
    for m, t, (r, c) in zip(methods, totals, summed):
        if c == 0:
            merged.append(None)
            continue
        proto = t if t is not None else ValidationResult(0.0, 0, m.name)
        merged.append(ValidationResult(r, int(c), proto.name))
    return merged


class Evaluator:
    """(reference ``optim/Evaluator.scala:37``)."""

    def __init__(self, model: Module):
        self.model = model

    def test(self, samples: Iterable[Sample],
             methods: Sequence[ValidationMethod],
             batch_size: int = 32
             ) -> List[Tuple[ValidationMethod, ValidationResult]]:
        batches = SampleToMiniBatch(batch_size)(iter(samples))
        return evaluate_dataset(self.model, batches, methods)


class Validator:
    """(reference ``optim/Validator.scala``) — over a MiniBatch dataset."""

    def __init__(self, model: Module, dataset):
        self.model = model
        self.dataset = dataset

    def test(self, methods: Sequence[ValidationMethod]):
        return evaluate_dataset(self.model, self.dataset, methods)


LocalValidator = Validator
DistriValidator = Validator
