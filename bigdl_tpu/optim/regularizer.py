"""Regularizers: L1/L2/L1L2 penalties added to gradients per layer.

Reference equivalent: ``optim/Regularizer.scala:87,175,186`` — the reference
mutates gradients in ``accGradParameters``; here regularizers contribute a
pure penalty term that the training-loss builder adds to the loss, so the
gradient contribution appears through autodiff (mathematically identical for
L2; for L1 the subgradient at 0 matches the reference's sign() convention).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Regularizer:
    def __init__(self, l1: float = 0.0, l2: float = 0.0):
        self.l1 = l1
        self.l2 = l2

    def penalty(self, params) -> jnp.ndarray:
        leaves = jax.tree_util.tree_leaves(params)
        total = jnp.zeros(())
        for p in leaves:
            if self.l1:
                total = total + self.l1 * jnp.abs(p).sum()
            if self.l2:
                total = total + 0.5 * self.l2 * (p * p).sum()
        return total


class L1Regularizer(Regularizer):
    def __init__(self, l1: float):
        super().__init__(l1=l1)


class L2Regularizer(Regularizer):
    def __init__(self, l2: float):
        super().__init__(l2=l2)


class L1L2Regularizer(Regularizer):
    pass
