"""Trigger: composable predicates over driver training state.

Reference equivalent: ``optim/Trigger.scala`` — everyEpoch:37,
severalIteration:63, maxEpoch:79, maxIteration:95, maxScore:107, minLoss:119,
plus and/or combinators.

The driver "state" is a plain dict with the reference's keys: ``epoch``,
``neval`` (1-based iteration counter), ``score``, ``Loss``.
"""

from __future__ import annotations

from typing import Callable, Dict


class Trigger:
    def __init__(self, fn: Callable[[Dict], bool], reads_loss: bool = False):
        self._fn = fn
        # drivers that pipeline loss reads (Engine.DispatchPipeline) must
        # flush before evaluating a loss-reading trigger, else it sees a
        # loss up to `depth` iterations stale; the flag propagates through
        # and_/or_ composition
        self.reads_loss = reads_loss

    def __call__(self, state: Dict) -> bool:
        return self._fn(state)

    def and_(self, other: "Trigger") -> "Trigger":
        return Trigger(lambda s: self(s) and other(s),
                       reads_loss=self.reads_loss or other.reads_loss)

    def or_(self, other: "Trigger") -> "Trigger":
        return Trigger(lambda s: self(s) or other(s),
                       reads_loss=self.reads_loss or other.reads_loss)

    def __and__(self, other):
        return self.and_(other)

    def __or__(self, other):
        return self.or_(other)


def every_epoch() -> Trigger:
    """Fires when the epoch counter advances (reference ``everyEpoch:37``)."""
    last = {"epoch": None}

    def fn(state):
        e = state.get("epoch")
        if last["epoch"] is None:
            last["epoch"] = e
            return False
        if e != last["epoch"]:
            last["epoch"] = e
            return True
        return False

    return Trigger(fn)


def several_iteration(interval: int) -> Trigger:
    """Every N iterations (reference ``severalIteration:63``)."""
    return Trigger(lambda s: s.get("neval", 0) % interval == 0
                   and s.get("neval", 0) > 0)


def max_epoch(n: int) -> Trigger:
    """End-condition: epoch > n (reference ``maxEpoch:79``)."""
    return Trigger(lambda s: s.get("epoch", 1) > n)


def max_iteration(n: int) -> Trigger:
    """End-condition: neval > n (reference ``maxIteration:95``)."""
    return Trigger(lambda s: s.get("neval", 1) > n)


def max_score(score: float) -> Trigger:
    """(reference ``maxScore:107``).  Inert until a validation has set
    ``score`` (the driver state initialises it to None)."""
    def fn(s):
        v = s.get("score")
        return v is not None and v > score
    return Trigger(fn)


def min_loss(loss: float) -> Trigger:
    """(reference ``minLoss:119``).  Inert until the first iteration has
    set ``Loss``.

    ``reads_loss=True``: the training drivers flush their dispatch
    pipeline before evaluating this trigger, so it always sees the latest
    iteration's loss — at the cost of serializing device reads (the
    pipelining win of ``bigdl.pipeline.depth`` does not apply while a
    loss-reading end trigger is installed)."""
    def fn(s):
        v = s.get("Loss")
        return v is not None and v < loss
    return Trigger(fn, reads_loss=True)
