"""Predictor: inference over sample collections.

Reference equivalents: ``optim/Predictor.scala:34`` / ``LocalPredictor.scala:37``.
"""

from __future__ import annotations

from typing import Iterable, List

import numpy as np

from bigdl_tpu.analysis.hostsync import host_pull
from bigdl_tpu.engine import DispatchPipeline
from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.evaluator import _eval_forward, _to_device
from bigdl_tpu.utils import compile_cache


class Predictor:
    """``fold_bn=True`` serves a CLONE of the model with every
    conv+BatchNorm pair folded into the convolution
    (:func:`bigdl_tpu.nn.fuse.fold_conv_bn`) — the inference-graph shape
    the TPU wants: one conv kernel per pair, no separate normalize pass.
    The caller's model is untouched (folding freezes BN at its running
    statistics, which would corrupt further training)."""

    def __init__(self, model: Module, fold_bn: bool = False):
        if fold_bn:
            from bigdl_tpu.nn.fuse import fold_conv_bn
            model = fold_conv_bn(model.clone_module().evaluate())
        self.model = model

    def _batches(self, dataset, batch_size: int):
        if isinstance(dataset, AbstractDataSet):
            it = dataset.data(train=False)
        else:
            it = iter(dataset)
        first = next(it, None)
        if first is None:
            return
        import itertools
        it = itertools.chain([first], it)
        if isinstance(first, Sample):
            yield from SampleToMiniBatch(batch_size)(it)
        else:
            yield from it

    def predict(self, dataset, batch_size: int = 32) -> np.ndarray:
        """Per-sample model outputs (reference ``predict``).

        Multi-host: a :class:`ShardedDataSet` holds only this process's
        partitions, so each process predicts its LOCAL records and keeps
        its local results — the reference's ``RDD[Sample] -> RDD[output]``
        shape, where distributed predictions stay distributed.  Params are
        host-detached for the local forward (a globally-placed replicated
        tree cannot mix with process-local batches in one computation)."""
        import jax
        was_training = self.model.train_mode
        self.model.evaluate()
        try:
            fwd = _eval_forward(self.model,
                                host_params=jax.process_count() > 1)
            # pipelined like evaluate_dataset: bounded in-flight batches
            # (unbounded dispatch would pin every output in device memory)
            outs: List[np.ndarray] = []

            def drain(item, _nxt):
                # one explicit device_get per batch (the same choke-point
                # discipline as evaluate_dataset's drain); padded rows
                # from a bucketed batch are sliced off host-side
                out = host_pull(item[0], what="predict outputs")
                outs.append(compile_cache.slice_rows(out, item[1]))

            buckets = compile_cache.configured_buckets()
            pipeline = DispatchPipeline(drain)
            for batch in self._batches(dataset, batch_size):
                n = batch.size()
                inputs = batch.get_input()
                if buckets:
                    # shape bucketing: the ragged final batch (and any
                    # caller-fed odd sizes) pad up to a configured
                    # bucket so serving hits only pre-compiled
                    # signatures — no per-request retrace
                    eff = compile_cache.bucket_size(n, buckets)
                    if eff != n:
                        inputs = compile_cache.pad_batch(inputs, n, eff)
                pipeline.push(fwd(_to_device(inputs)), n)
            pipeline.flush()
            if not outs:
                # an empty dataset predicts an empty array, not None:
                # ``_batches`` ends without yielding, so nothing above
                # ran — callers doing ``len(out)`` / ``np.concatenate``
                # downstream must keep working
                return np.zeros((0,))
            return np.concatenate(outs, axis=0)
        finally:
            if was_training:
                self.model.training()

    def predict_class(self, dataset, batch_size: int = 32) -> np.ndarray:
        """1-based argmax class ids (reference ``predictClass``)."""
        out = self.predict(dataset, batch_size)
        if out.size == 0:
            # argmax over a zero-length axis raises; an empty dataset
            # classifies to an empty id array, mirroring predict()
            return np.zeros((0,), dtype=np.int64)
        return out.argmax(axis=-1) + 1
