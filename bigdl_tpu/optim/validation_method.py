"""ValidationMethod: evaluation metrics with mergeable result algebra.

Reference equivalent: ``optim/ValidationMethod.scala`` — Top1Accuracy:170,
Top5Accuracy:218, Loss:312, MAE:332; results carry ``+`` so per-shard partial
results reduce on the driver (``:72-115``).

TPU-native: each metric also exposes a pure, batched ``accumulate`` returning
(correct_count, total_count) arrays, so a metric can run INSIDE a jitted,
sharded eval step and be psum-reduced over the mesh — rather than pulling
logits to the host per batch.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


class ValidationResult:
    """Mergeable (result, count) pair (reference ``ContiguousResult``)."""

    def __init__(self, result: float, count: int, name: str = ""):
        self.result = float(result)
        self.count = int(count)
        self.name = name

    def __add__(self, other: "ValidationResult") -> "ValidationResult":
        return ValidationResult(self.result + other.result,
                                self.count + other.count, self.name)

    def final_result(self) -> float:
        return self.result / max(self.count, 1)

    def __repr__(self):
        return f"{self.final_result():.6f} ({self.name}: {self.result}/{self.count})"


class ValidationMethod:
    """Base; ``apply(output, target) -> ValidationResult`` on host arrays."""

    name = "ValidationMethod"

    def apply(self, output, target) -> ValidationResult:
        raise NotImplementedError

    def __call__(self, output, target) -> ValidationResult:
        return self.apply(output, target)

    def __repr__(self):
        return self.name

    def clone(self):
        import copy
        return copy.deepcopy(self)


def _squeeze_logits(output) -> np.ndarray:
    # the evaluator hands metrics HOST arrays (one explicit device_get per
    # validation step); this asarray is a free view, not a device sync
    out = np.asarray(output)
    if out.ndim == 1:
        out = out[None, :]
    return out


class Top1Accuracy(ValidationMethod):
    """(reference ``Top1Accuracy:170``; labels 1-based)."""

    name = "Top1Accuracy"

    def apply(self, output, target) -> ValidationResult:
        out = _squeeze_logits(output)
        tgt = np.asarray(target).reshape(-1)
        pred = out.argmax(axis=-1) + 1
        correct = int((pred == tgt.astype(np.int64)).sum())
        return ValidationResult(correct, tgt.shape[0], self.name)


class Top5Accuracy(ValidationMethod):
    """(reference ``Top5Accuracy:218``)."""

    name = "Top5Accuracy"

    def apply(self, output, target) -> ValidationResult:
        out = _squeeze_logits(output)
        tgt = np.asarray(target).reshape(-1).astype(np.int64)
        top5 = np.argsort(-out, axis=-1)[:, :5] + 1
        correct = int((top5 == tgt[:, None]).any(axis=1).sum())
        return ValidationResult(correct, tgt.shape[0], self.name)


class Loss(ValidationMethod):
    """Criterion value as a metric (reference ``Loss:312``)."""

    name = "Loss"

    def __init__(self, criterion=None):
        if criterion is None:
            from bigdl_tpu.nn.criterion import ClassNLLCriterion
            criterion = ClassNLLCriterion()
        self.criterion = criterion

    def apply(self, output, target) -> ValidationResult:
        # the criterion computes on device; the result comes back through
        # the explicit choke point instead of an implicit float() sync
        from bigdl_tpu.analysis.hostsync import host_pull
        loss = float(host_pull(self.criterion.apply(jnp.asarray(output),
                                                    jnp.asarray(target)),
                               what="Loss validation metric"))
        n = np.asarray(target).reshape(-1).shape[0]
        return ValidationResult(loss * n, n, self.name)


class MAE(ValidationMethod):
    """Mean absolute error on predicted class (reference ``MAE:332``)."""

    name = "MAE"

    def apply(self, output, target) -> ValidationResult:
        out = _squeeze_logits(output)
        tgt = np.asarray(target).reshape(-1)
        pred = out.argmax(axis=-1) + 1
        err = float(np.abs(pred - tgt).sum())
        return ValidationResult(err, tgt.shape[0], self.name)


class TreeNNAccuracy(ValidationMethod):
    """Accuracy of a Tree/Recursive NN measured at the ROOT node only
    (reference ``TreeNNAccuracy``, ``optim/ValidationMethod.scala:118``):
    output (B, nodes, C) — node 1 is the root; binary single-logit outputs
    threshold at 0.5, multi-class outputs argmax; labels 1-based."""

    name = "TreeNNAccuracy"

    def apply(self, output, target) -> ValidationResult:
        out = np.asarray(output)
        tgt = np.asarray(target)
        if tgt.ndim >= 2:
            tgt = tgt[:, 0]
        tgt = tgt.reshape(-1)
        if out.ndim == 3:
            root = out[:, 0]              # (B, C)
        elif out.ndim == 2:
            root = out[0][None, :]        # single sample: first node row
            tgt = tgt[:1]
        else:
            raise ValueError(f"TreeNNAccuracy: bad output rank {out.ndim}")
        if root.shape[-1] == 1:
            pred = (root[..., 0] >= 0.5).astype(np.int64)
        else:
            pred = root.argmax(axis=-1) + 1
        correct = int((pred == tgt.astype(np.int64)).sum())
        return ValidationResult(correct, tgt.shape[0], self.name)
