"""bigdl_tpu.optim — training orchestration (SURVEY §2.7)."""

from bigdl_tpu.optim.optim_method import (OptimMethod, SGD, Adagrad, Adadelta,
                                          Adam, Adamax, RMSprop, LBFGS,
                                          LearningRateSchedule, Default, Step,
                                          MultiStep, EpochStep, EpochDecay,
                                          Poly, Exponential, NaturalExp,
                                          EpochSchedule, Regime, Plateau)
from bigdl_tpu.optim.trigger import (Trigger, every_epoch, several_iteration,
                                     max_epoch, max_iteration, max_score,
                                     min_loss)
from bigdl_tpu.optim.validation_method import (ValidationMethod,
                                               ValidationResult, Top1Accuracy,
                                               Top5Accuracy, Loss, MAE,
                                               TreeNNAccuracy)
from bigdl_tpu.optim.regularizer import (Regularizer, L1Regularizer,
                                         L2Regularizer, L1L2Regularizer)
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optimizer import (Optimizer, LocalOptimizer, Checkpoint,
                                       DivergenceError)
from bigdl_tpu.optim.evaluator import (Evaluator, Validator, LocalValidator,
                                       DistriValidator, evaluate_dataset)
from bigdl_tpu.optim.predictor import Predictor

LocalPredictor = Predictor

__all__ = [
    "OptimMethod", "SGD", "Adagrad", "Adadelta", "Adam", "Adamax", "RMSprop",
    "LBFGS", "LearningRateSchedule", "Default", "Step", "MultiStep",
    "EpochStep", "EpochDecay", "Poly", "Exponential", "NaturalExp",
    "EpochSchedule", "Regime", "Plateau", "Trigger", "every_epoch",
    "several_iteration", "max_epoch", "max_iteration", "max_score",
    "min_loss", "ValidationMethod", "ValidationResult", "Top1Accuracy",
    "Top5Accuracy", "Loss", "MAE", "TreeNNAccuracy", "Regularizer", "L1Regularizer",
    "L2Regularizer", "L1L2Regularizer", "Metrics", "Optimizer",
    "LocalOptimizer", "Checkpoint", "DivergenceError", "Evaluator",
    "Validator",
    "LocalValidator", "DistriValidator", "evaluate_dataset", "Predictor",
    "LocalPredictor",
]
