"""Optimizer: training orchestration base + the single-process LocalOptimizer.

Reference equivalents: ``optim/Optimizer.scala:42,268`` (abstract base with
fluent setters + factory choosing Distri vs Local by dataset type) and
``optim/LocalOptimizer.scala:41`` (single-JVM trainer: thread-replica models
sharing one weight storage, chunked gradient sums, whole-vector optim step).

TPU-native redesign of the hot path: the reference's intra-node replica tier
(clone N models, slice the batch, sum gradients multi-threaded) collapses
into ONE jitted step — forward + loss + backward + optimizer update fused by
XLA (SURVEY §7 stage 1 note: replicas become "one params pytree, one bigger
per-chip batch").  The driver loop, triggers, checkpointing, validation, and
summary protocol are kept 1:1.
"""

from __future__ import annotations

import logging
import math
import os
import random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import telemetry
from bigdl_tpu.telemetry import incident
from bigdl_tpu.engine import DispatchPipeline
from bigdl_tpu.engine import to_device as _to_device
from bigdl_tpu.dataset.dataset import AbstractDataSet, LocalDataSet, ShardedDataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.nn.module import Container, Criterion, Module
from bigdl_tpu.optim import trigger as triggers
from bigdl_tpu.utils import chaos as _chaos
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optim_method import OptimMethod, SGD
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation_method import ValidationMethod, ValidationResult
from bigdl_tpu.resources import (GOVERNOR as _governor, DeviceMemoryError,
                                 HostMemoryError)
from bigdl_tpu.resources import storage as _resource_storage

logger = logging.getLogger("bigdl_tpu")

#: injectable for tests (the backoff suite must not really sleep)
_sleep = time.sleep


class DivergenceError(RuntimeError):
    """Raised by the driver loop after K consecutive non-finite losses —
    caught by the retry loop, which restores the latest valid snapshot
    (graceful degradation instead of silent NaN propagation)."""


def _retry_backoff(attempt: int, base: float, cap: float,
                   rand: Optional[float] = None) -> float:
    """Capped exponential backoff with jitter for the failure-retry loop.

    Attempt ``a`` waits ``min(base * 2**(a-1), cap)`` scaled by a jitter
    factor in [0.5, 1.0] — a fleet of workers restarting off one failed
    storage backend must not stampede it in lockstep.  A cap BELOW the
    base wins (the operator asked for fast retries); a non-positive cap
    means uncapped.  ``rand`` pins the jitter for tests."""
    if base <= 0:
        return 0.0
    r = rand if rand is not None else random.random()
    interval = base * (2.0 ** (max(attempt, 1) - 1))
    if cap > 0:
        interval = min(interval, cap)
    return interval * (0.5 + 0.5 * r)


def is_writer_process() -> bool:
    """Single-writer discipline for externally-visible artifacts.

    In the reference, checkpoints and TensorBoard events are written exactly
    once, from the driver JVM (``optim/DistriOptimizer.scala:394-416`` and
    ``:426-456`` — executor code never writes).  The multi-controller SPMD
    rebuild runs the full driver body in EVERY process, so file-producing
    calls (checkpoint snapshots, summary events, parameter histograms) are
    gated here on process 0.  Trigger *decisions* stay ungated — every
    process must reach the same publish/validation sync points or the
    collectives inside them deadlock; only the filesystem writes are
    single-writer.  Single-process this is always True.
    """
    return jax.process_index() == 0


def cast_floats(tree, dtype):
    """Cast float leaves of a pytree (mixed-precision compute casts)."""
    def f(x):
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(f, tree)


def mixed_precision_forward(model: Module, params, inputs, mstate,
                            precision, training: bool, rng):
    """Forward in the compute precision, loss-side outputs back in fp32.

    bf16: parameters/inputs/state are cast down for the forward (autodiff
    casts gradients back up, so the update sees fp32 master-weight grads);
    outputs and new state return as fp32 for the loss and the carries.
    """
    if precision == "bf16":
        cp = cast_floats(params, jnp.bfloat16)
        cx = cast_floats(inputs, jnp.bfloat16)
        # module state (BatchNorm running statistics) stays fp32 like the
        # master weights: EMA increments below bf16 resolution must not
        # round away, and fp32 state promotes the EMA arithmetic itself
        out, new_mstate = model.apply(cp, cx, mstate, training=training,
                                      rng=rng)
        out = cast_floats(out, jnp.float32)
        from bigdl_tpu.utils import config
        if (config.get_bool("bigdl.chaos.f32Upcast", False)
                and getattr(out, "ndim", 0) >= 2):
            # audit fault injection: an f32 matmul smuggled into a
            # declared-bf16 program — numerically an identity, but an
            # f32 dot_general in the lowered text, exactly the drift
            # the precision pass exists to catch
            eye = jnp.eye(jnp.shape(out)[-1], dtype=jnp.float32)
            out = out @ eye
        return out, cast_floats(new_mstate, jnp.float32)
    return model.apply(params, inputs, mstate, training=training, rng=rng)


def moe_aux_penalty(model: Module, new_mstate, weight: float):
    """MoE load-balancing term: ``weight`` x the sum of every declared
    ``aux_loss`` diagnostic in the post-forward state (Switch's balancing
    objective; without this in the loss, routing feels zero pressure and
    expert collapse is the textbook outcome).  Zero when the model has no
    MoE (the walk finds nothing at trace time, adding no ops)."""
    from bigdl_tpu.nn.module import collect_diagnostics
    aux = collect_diagnostics(model, new_mstate, "aux_loss")
    if not aux or weight == 0.0:
        return jnp.zeros(())
    return weight * sum(aux)


def all_finite(*trees) -> jnp.ndarray:
    """Scalar bool: every float leaf of every tree is finite.  The
    divergence guard's trace-time predicate — cheap relative to the step
    (one reduction per leaf, fused by XLA).  Empty and integer-only
    trees are vacuously finite and return a CONSTANT True without
    building a single device op — callers branch on the guard at trace
    time, and a float-free tree must not cost a device reduction (or a
    tracer) to say nothing."""
    ok = None
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            leaf = jnp.asarray(leaf)
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                fin = jnp.all(jnp.isfinite(leaf))
                ok = fin if ok is None else jnp.logical_and(ok, fin)
    if ok is None:
        return np.bool_(True)
    return ok


def select_tree(ok, new_tree, old_tree):
    """Per-leaf ``where(ok, new, old)`` — the divergence guard's in-step
    skip: when the step produced a non-finite loss or gradient, every
    carry keeps its pre-step value (``where(True, new, old)`` is exactly
    ``new``, so a healthy step is numerically untouched)."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new_tree, old_tree)


def regularization_penalty(module: Module, params) -> jnp.ndarray:
    """Sum per-layer regularizer penalties over the module tree
    (reference applies them in each layer's accGradParameters,
    ``optim/Regularizer.scala``; here they join the loss so autodiff
    produces the identical gradient contribution)."""
    total = jnp.zeros(())
    if isinstance(module, Container):
        for i, c in enumerate(module.children):
            total = total + regularization_penalty(c, params[i])
    else:
        wreg = getattr(module, "w_regularizer", None)
        breg = getattr(module, "b_regularizer", None)
        if wreg is not None and isinstance(params, dict):
            w = {k: v for k, v in params.items() if k != "bias"}
            total = total + wreg.penalty(w)
        if breg is not None and isinstance(params, dict) and "bias" in params:
            total = total + breg.penalty(params["bias"])
    return total


class Checkpoint:
    """model.<neval> / optimMethod.<neval> snapshot protocol
    (reference ``optim/DistriOptimizer.scala:394-416``), hardened into
    verified units by :class:`~bigdl_tpu.utils.checkpoint_manager.
    CheckpointManager`: every snapshot carries a CRC32C manifest plus a
    commit marker written last, restore scans newest→oldest skipping
    torn/uncommitted/corrupt snapshots, ``keep_last`` garbage-collects
    old committed snapshots, and ``async_write`` moves serialization+IO
    onto a background writer (errors re-raise at the next save and at
    exit).

    ``path`` may be local or any fsspec scheme (``hdfs://``, ``s3://``,
    ``memory://``, …) — the reference checkpoints to HDFS the same way
    (``File.saveToHdfs:106``); listing/joining go through
    ``utils.file_io`` so ``latest()`` resolves remotely too."""

    #: seconds a ``.tmp_bigdl`` temp must sit untouched before the sweep
    #: may reclaim it.  An atomic save holds its temp open for seconds at
    #: most; an hour-old temp is an orphan from a hard-killed writer, not
    #: another live job's in-flight write (two jobs pointed at one dir, a
    #: stalled-but-alive writer) — sweeping those would break THEIR rename.
    TEMP_SWEEP_AGE_S = 3600.0

    def __init__(self, path: str, trigger: Trigger, isOverwrite: bool = True,
                 keep_last: Optional[int] = None,
                 async_write: Optional[bool] = None):
        from bigdl_tpu.utils.checkpoint_manager import CheckpointManager
        self.path = path
        self.trigger = trigger
        self.overwrite = isOverwrite
        self.manager = CheckpointManager(path, keep_last=keep_last,
                                         async_write=async_write,
                                         overwrite=isOverwrite)
        self.manager.TEMP_SWEEP_AGE_S = self.TEMP_SWEEP_AGE_S

    def save(self, model: Module, optim: OptimMethod, neval: int,
             topology=None) -> None:
        self.manager.save(model, optim, neval, topology=topology)

    def latest(self) -> Optional[Tuple[str, str, int]]:
        """Newest snapshot that is a complete pair, committed, and
        checksum-clean (``latest_valid`` semantics: one torn write can
        never brick recovery)."""
        return self.manager.latest_valid()

    latest_valid = latest

    def join(self, raise_errors: bool = True) -> None:
        """Drain the async writer; deferred write errors re-raise here."""
        self.manager.join(raise_errors=raise_errors)


class Optimizer:
    """Abstract trainer base (reference ``optim/Optimizer.scala:42``).

    The ``Optimizer(...)`` factory (``apply``, reference ``:268``) picks
    :class:`LocalOptimizer` or the distributed trainer by dataset type.
    """

    def __init__(self, model: Module, dataset: AbstractDataSet,
                 criterion: Criterion):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.optim_method: OptimMethod = SGD()
        self.end_when: Trigger = triggers.max_iteration(100)
        self.checkpoint: Optional[Checkpoint] = None
        self.validation_trigger: Optional[Trigger] = None
        self.validation_dataset: Optional[AbstractDataSet] = None
        self.validation_methods: Optional[List[ValidationMethod]] = None
        self.train_summary = None
        self.validation_summary = None
        self.drop_percentage: float = 0.0
        self.max_drop_percentage: float = 0.0
        self.metrics = Metrics()
        self.precision: Optional[str] = None   # None = fp32; "bf16" = mixed
        self.moe_aux_weight: float = 0.01      # Switch paper's alpha
        self._step_fn = None
        self._profile_dir: Optional[str] = None
        self._profile_start: int = 10
        self._profile_n: int = 3
        #: recompile sentinel wrapped around the fused step (analysis pass 1)
        self._retrace_sentinel = None
        #: the unwrapped jitted step (the sentinel hides .lower) — the
        #: telemetry FLOPs estimate lowers THIS
        self._raw_step_fn = None
        #: fused-step FLOPs from cost_analysis (bigdl.telemetry.mfu)
        self._step_flops: Optional[float] = None
        self._want_step_flops = False
        #: per-run step-time decomposition (bigdl_tpu.telemetry)
        self._step_account = None
        #: microbatch re-plan state (resources.microbatch): the fused
        #: step runs as k gradient-accumulation chunks after a device
        #: OOM; 1 = full-batch (the normal plan)
        self._microbatch_k: int = 1
        #: global batch size observed by the last fetch — the re-plan
        #: needs it to pick a k that divides the batch
        self._plan_batch_size: int = 0

    # -- fluent setters (reference Optimizer.scala fluent API) ------------

    def set_trace_profile(self, log_dir: str, start_iteration: int = 10,
                          n_iterations: int = 3) -> "Optimizer":
        """Capture a ``jax.profiler`` device/host trace of ``n_iterations``
        steady-state training iterations into ``log_dir`` (xplane + trace
        viewer files; open with TensorBoard's profile plugin or Perfetto).

        TPU-native counterpart of the per-module ns timing (SURVEY §5.1):
        the per-module clocks attribute time WITHIN the model graph, the
        trace shows the whole step — XLA fusions, collectives, host gaps.
        ``start_iteration`` defaults past compile+warmup so the captured
        window is the steady state the throughput logs report."""
        if n_iterations < 1:
            raise ValueError(f"n_iterations must be >= 1, got {n_iterations}")
        if start_iteration < 1:
            raise ValueError(
                f"start_iteration must be >= 1 (iteration counting is "
                f"1-based), got {start_iteration}")
        self._profile_dir = log_dir
        self._profile_start = start_iteration
        self._profile_n = n_iterations
        return self

    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        self.optim_method = method
        self._step_fn = None  # the jitted step closes over the optim method
        return self

    def set_end_when(self, trigger: Trigger) -> "Optimizer":
        self.end_when = trigger
        return self

    def set_checkpoint(self, path: str, trigger: Trigger,
                       isOverwrite: bool = True,
                       keep_last: Optional[int] = None,
                       async_write: Optional[bool] = None) -> "Optimizer":
        """``keep_last``: retain only the N newest committed snapshots
        (default ``bigdl.checkpoint.keepLast``; 0 keeps all).
        ``async_write``: serialize+write snapshots on a background thread
        so the train step never blocks on (possibly remote) IO — writer
        errors re-raise at the next save and at exit (default
        ``bigdl.checkpoint.asyncWrite``)."""
        self.checkpoint = Checkpoint(path, trigger, isOverwrite,
                                     keep_last=keep_last,
                                     async_write=async_write)
        return self

    def set_validation(self, trigger: Trigger, dataset: AbstractDataSet,
                       methods: List[ValidationMethod],
                       batch_size: Optional[int] = None) -> "Optimizer":
        self.validation_trigger = trigger
        if isinstance(dataset, (list, tuple)):
            dataset = LocalDataSet(dataset)
        if batch_size is not None and not _yields_minibatches(dataset):
            from bigdl_tpu.dataset.transformer import SampleToMiniBatch
            dataset = dataset.transform(SampleToMiniBatch(batch_size))
        self.validation_dataset = dataset
        self.validation_methods = methods
        return self

    def set_train_summary(self, summary) -> "Optimizer":
        self.train_summary = summary
        return self

    def set_validation_summary(self, summary) -> "Optimizer":
        self.validation_summary = summary
        return self

    def set_precision(self, precision: Optional[str]) -> "Optimizer":
        """Mixed-precision training: ``"bf16"`` runs forward/backward in
        bfloat16 (the MXU's native multiply format; ~1.8x ResNet-50
        throughput measured on v5e) while master weights, the loss, and the
        optimizer update stay float32.  The reference's fp16 existed only on
        the wire (``parameters/FP16CompressedTensor.scala``); on TPU reduced
        precision lives in the compute itself."""
        if precision not in (None, "bf16"):
            raise ValueError(f"unsupported precision {precision!r}")
        self.precision = precision
        self._step_fn = None
        return self

    def set_moe_aux_weight(self, weight: float) -> "Optimizer":
        """Weight of the MoE load-balancing auxiliary loss folded into the
        objective (:func:`moe_aux_penalty`).  Default 0.01 — the Switch
        Transformer paper's alpha; 0 disables the pressure (diagnostic
        stays readable in module state)."""
        self.moe_aux_weight = float(weight)
        self._step_fn = None
        return self

    def set_drop_module_percentage(self, drop_p: float,
                                   max_drop_p: float) -> "Optimizer":
        """Straggler dropping (reference ``setDropModuleProperty``).  Kept for
        API parity: synchronous XLA collectives have no intra-step stragglers
        (SURVEY §7 stage 4), so this is recorded but inert."""
        self.drop_percentage = drop_p
        self.max_drop_percentage = max_drop_p
        return self

    def optimize(self) -> Module:
        """Train with failure retry (reference
        ``optim/DistriOptimizer.scala:750-816``): on a non-argument error the
        newest VALID ``model.N``/``optimMethod.N`` snapshot is restored and
        training resumes, up to ``bigdl.failure.retryTimes`` attempts.
        Waits between attempts follow capped exponential backoff with
        jitter (:func:`_retry_backoff`), and — mirroring the reference's
        ``retryNum`` reset — the attempt counter resets whenever training
        reaches NEW ground (``evalCounter`` beyond any previous
        attempt's high-water mark), so a long healthy run is never
        killed by unrelated failures hours apart, while a deterministic
        failure that replays the same stretch after every rollback still
        exhausts the budget.

        Failure taxonomy (``utils/elastic.py``): *divergence* and a
        watchdog-aborted hung step restore-and-retry here; *preemption*
        (:class:`~bigdl_tpu.utils.elastic.Preempted` — the driver already
        drained and published) commits a final verified snapshot plus a
        resumable marker within ``bigdl.elastic.gracePeriod`` and
        re-raises: the scheduler said leave, not rewind."""
        from bigdl_tpu.utils import config, elastic
        retry_times = config.get_int("bigdl.failure.retryTimes", 5)
        base = config.get_float("bigdl.failure.retryTimeInterval", 120.0)
        cap = config.get_float("bigdl.failure.maxRetryInterval", 900.0)
        # a fresh optimize() starts clean: a preemption flag left over
        # from a previous run in this process (or a marker from the
        # preempted lifetime we are resuming) must not instantly re-drain
        elastic.clear_preemption()
        if self.checkpoint is not None and is_writer_process():
            elastic.clear_preemption_marker(self.checkpoint.path)
        try:
            with elastic.PreemptionHandler():
                return self._optimize_with_retry(retry_times, base, cap)
        except BaseException:
            # already unwinding: drain the writer but never let a deferred
            # write error mask the original failure
            if self.checkpoint is not None:
                try:
                    self.checkpoint.join(raise_errors=False)
                except Exception:  # pragma: no cover - defensive
                    pass
            raise

    def _optimize_with_retry(self, retry_times, base, cap) -> Module:
        from bigdl_tpu.utils import elastic
        attempt = 0
        high_water = None   # furthest evalCounter any attempt reached
        while True:
            try:
                result = self._optimize()
            except (ValueError, TypeError, KeyboardInterrupt):
                # reference: IllegalArgumentException aborts immediately
                raise
            except HostMemoryError:
                # host memory exhausted even at depth 1 — no ring can
                # shrink below one item, so a retry replays the same
                # allocation; surface the structured error immediately
                raise
            except DeviceMemoryError as e:
                # RESOURCE fault, not divergence: the same program would
                # OOM forever, so retrying costs no budget and waits no
                # backoff — the answer is a microbatch re-plan (split the
                # global batch into k accumulation chunks).  next_k's
                # doubling schedule bounds the loop: once per-sample has
                # been tried the re-plan returns False and the fault is
                # fatal.
                heal_t0 = time.monotonic()
                if not self._replan_microbatch(e):
                    raise
                restored = self._restore_latest_checkpoint()
                if not restored and self._params_dead():
                    # the OOMed dispatch donated-and-deleted the carries
                    # and there is no snapshot to reload them from
                    raise
                heal_ms = (time.monotonic() - heal_t0) * 1000.0
                telemetry.gauge(
                    "Resources/oom_replan_ms",
                    help="device-OOM detection to re-planned-step "
                         "readiness (re-plan + restore)").set(heal_ms)
                incident.record("optim/oom_replan", restored=restored,
                                heal_ms=round(heal_ms, 2))
                continue
            except elastic.Preempted:
                # the driver drained and published before raising; commit
                # the grace-period snapshot and leave — preemption is an
                # eviction, not a fault, so no retry and no restore
                incident.record("optim/preempted",
                                reason=elastic.preemption_reason())
                self._commit_preemption_snapshot()
                incident.maybe_dump("preemption", reason="preemption")
                raise
            except Exception as e:
                from bigdl_tpu.integrity import (IntegrityError,
                                                 ReplicaDesyncError)
                cur = self.optim_method.state.get("evalCounter", 0)
                if (not isinstance(e, (DivergenceError, IntegrityError))
                        and high_water is not None and cur > high_water):
                    # NEW ground — training got further than any
                    # previous attempt, so this is a fresh fault, not
                    # the same one looping (reference retryNum reset
                    # on state-version advance, :772-776).  The
                    # baseline must be the high-water mark across
                    # attempts: replayed ground after a rollback is
                    # not progress, or a deterministic failure pinned
                    # one step past the newest snapshot would reset
                    # the budget every cycle and retry forever.
                    # Divergence NEVER resets the budget: guard-
                    # skipped iterations still advance the counters
                    # (frozen params, moving evalCounter), so a
                    # persistently-NaN pipeline would otherwise creep
                    # the high-water mark every restore cycle and
                    # loop unbounded.
                    attempt = 0
                high_water = cur if high_water is None else max(
                    high_water, cur)
                attempt += 1
                if attempt >= retry_times:
                    incident.record("optim/retries_exhausted",
                                    attempt=attempt,
                                    error=type(e).__name__)
                    incident.maybe_dump("optim/retries_exhausted",
                                        reason=type(e).__name__)
                    raise
                if (isinstance(e, ReplicaDesyncError)
                        and getattr(e, "healed", False)):
                    # the trainer already re-broadcast canonical state
                    # from the agreeing majority and rewound the eval
                    # counter — a checkpoint restore would throw away
                    # the surviving replicas' newer, valid ground
                    incident.record("optim/desync_heal", attempt=attempt,
                                    error=type(e).__name__)
                    interval = _retry_backoff(attempt, base, cap)
                    logger.warning(
                        "Replica desync healed in place (attempt %d/%d); "
                        "re-entering training in %.1fs: %s", attempt,
                        retry_times, interval, e)
                    _sleep(interval)
                    continue
                heal_t0 = time.monotonic()
                restored = self._restore_latest_checkpoint()
                if restored and isinstance(e, IntegrityError):
                    telemetry.gauge(
                        "Integrity/heal_ms",
                        help="detection-to-heal latency of the last "
                             "integrity fault (restore or re-broadcast)"
                    ).set((time.monotonic() - heal_t0) * 1000.0)
                if not restored and self._params_dead():
                    # the jitted step donates its carries: without a
                    # snapshot to reload, the in-memory params are gone
                    # — retrying would fail on deleted buffers, so
                    # surface the original
                    raise
                incident.record("optim/retry_restore", attempt=attempt,
                                error=type(e).__name__, restored=restored)
                interval = _retry_backoff(attempt, base, cap)
                logger.exception(
                    "Training failed (attempt %d/%d); %s and retrying "
                    "in %.1fs", attempt, retry_times,
                    "restored latest valid checkpoint" if restored else
                    "resuming from last published state", interval)
                _sleep(interval)
                continue
            # clean exit: surface any deferred async-writer error
            # BEFORE reporting success — a "finished" run whose last
            # snapshot silently failed to land is a lie
            if self.checkpoint is not None:
                self.checkpoint.join()
            return result

    def _replan_microbatch(self, e: DeviceMemoryError) -> bool:
        """Answer a :class:`DeviceMemoryError` with the next microbatch
        plan: the global batch of B samples re-runs as k equal
        accumulation chunks (``resources.microbatch`` — Kahan-compensated
        mean gradient, ONE optimizer update, numerics allclose to the
        full-batch step).  Invalidates the built step and the retrace
        sentinel so the re-planned program compiles as a NEW signature
        with its own warmup — the re-plan must never trip the strict
        retrace gate.  Returns False when no further split exists
        (already per-sample, or no batch observed yet)."""
        from bigdl_tpu.resources import microbatch as _microbatch
        bsz = int(self._plan_batch_size or 0)
        if bsz <= 0:
            return False
        k = _microbatch.next_k(bsz, self._microbatch_k)
        if k is None:
            return False
        prev = self._microbatch_k
        self._microbatch_k = k
        self._step_fn = None           # rebuild with the k-chunk plan
        self._retrace_sentinel = None  # fresh warmup for the new program
        telemetry.counter(
            "Resources/microbatch_replans",
            help="device-OOM-driven microbatch re-plans this process").inc()
        telemetry.gauge(
            "Resources/microbatch_k",
            help="gradient-accumulation chunks per step after OOM "
                 "re-planning (1 = full batch)").set(k)
        logger.warning(
            "Device memory exhausted (%s) — re-planning the fused step: "
            "global batch %d now runs as %d accumulation chunk(s) of %d "
            "samples (was k=%d)", e, bsz, k, bsz // k, prev)
        return True

    def _commit_preemption_snapshot(self) -> None:
        """The grace-period exit: the driver already flushed its dispatch
        pipeline and published the carries before raising ``Preempted``,
        so the live model/optim shells hold the newest weights — commit
        them as a final verified snapshot, drain the async writer, and
        drop the resumable marker.  Multi-host note: preemption unwinds
        every rank (the scheduler signals the whole slice); only the
        writer process touches the store, and no barrier is added here —
        peers may already be dying, and a barrier against the dead hangs
        the grace window."""
        from bigdl_tpu.utils import elastic
        if self.checkpoint is None:
            logger.warning(
                "Preempted with no checkpoint configured — state of this "
                "run is lost (set_checkpoint enables the grace-period "
                "snapshot)")
            return
        # the grace window opened when preemption was REQUESTED: the
        # drain the driver already ran (pipeline flush + publish) spent
        # part of it, and the overshoot report must say so
        opened = elastic.preemption_requested_at()
        deadline = ((opened if opened is not None else time.monotonic())
                    + elastic.grace_period())
        neval = self.optim_method.state.get("evalCounter", 0)
        committed = True
        if is_writer_process():
            with elastic.timed("preempt_snapshot"):
                # a failed write (sync save raising, or an async write
                # surfacing at join) must neither drop the marker — a
                # marker naming a snapshot that never landed would turn
                # a botched drain into a trusted orderly preemption —
                # nor replace the Preempted unwinding this frame: the
                # run IS preempted either way, resume falls back to the
                # newest earlier valid snapshot
                try:
                    self.checkpoint.save(self.model, self.optim_method,
                                         neval,
                                         topology=self._topology_meta())
                    # the marker must only land AFTER the snapshot is
                    # committed
                    self.checkpoint.join()
                except Exception:
                    committed = False
                    logger.exception(
                        "Grace-period snapshot %d failed to commit — "
                        "NOT writing the preemption marker; resume will "
                        "fall back to the newest earlier valid snapshot",
                        neval)
            if committed:
                elastic.write_preemption_marker(self.checkpoint.path, neval)
        overshoot = time.monotonic() - deadline
        status = ("snapshot %d is committed" % neval if committed else
                  "snapshot %d FAILED to commit" % neval)
        if overshoot > 0:
            logger.warning(
                "Preemption drain exceeded bigdl.elastic.gracePeriod by "
                "%.1fs — the scheduler may have killed peers already; "
                "%s", overshoot, status)
        else:
            logger.info(
                "Preemption drain complete: %s with %.1fs of the grace "
                "period to spare", status, -overshoot)

    def _topology_meta(self) -> Optional[Dict[str, Any]]:
        """The saving topology recorded in snapshot manifests
        (``elastic.describe_topology``); distributed trainers override
        with their mesh so restores onto a different device count can
        reshard — the local trainer has no mesh to record."""
        from bigdl_tpu.utils import elastic
        return elastic.describe_topology(step="local")

    def _sync_dataset_epoch(self) -> None:
        """Cross-restart batch-stream parity, part 2: a RESUMED run
        fast-forwards the dataset's shuffle round to ``epoch - 1`` so
        its first ``reset_epoch`` draws epoch E's permutation — the one
        the interrupted run trained (and an uninterrupted run would
        train), not round 1's.  ``ShardedDataSet`` shuffles are pure in
        ``(seed, round)`` which makes the replay exact; ``LocalDataSet``
        draws from the stateful thread-local generator and has no round
        protocol (no-op here) — bit-exact resume parity is the sharded
        dataset's contract."""
        epoch = self.optim_method.state.get("epoch", 1)
        sync = getattr(self.dataset, "set_shuffle_round", None)
        if sync is not None:
            # unconditional, epoch 1 included: an in-process retry that
            # restores into epoch 1 reuses a dataset whose round already
            # advanced — without the rewind the replayed epoch would
            # draw round 2's permutation
            sync(epoch - 1)

    def _optimize(self) -> Module:
        raise NotImplementedError

    def _arm_retrace(self, step_fn, label: str):
        """Wrap a fused jitted step with the recompile sentinel
        (``bigdl.analysis.retrace``): post-warmup signature drift raises
        (strict) or logs a structured shape/dtype/weak-type diff (warn),
        surfaced as ``Analysis/retraces`` in TrainSummary.  Host-driven
        feval methods (LBFGS) are not jitted per-step, so they pass
        through unwrapped."""
        self._raw_step_fn = step_fn
        if getattr(self.optim_method, "requires_feval", False):
            return step_fn
        from bigdl_tpu.analysis.retrace import RetraceSentinel
        sentinel = RetraceSentinel.from_config(
            f"{type(self).__name__}[{label}]")
        if sentinel is None:
            return step_fn
        self._retrace_sentinel = sentinel
        return sentinel.wrap(step_fn)

    def _estimate_step_flops(self, args: Tuple) -> None:
        """One-shot FLOPs estimate of the fused step from the lowered
        HLO's ``cost_analysis()`` — a re-trace + lower, never a second
        XLA compile (array args become ``ShapeDtypeStruct``s, so no
        device data moves).  Enabled by ``bigdl.telemetry.mfu``; the
        drain logs achieved TFLOP/s (or MFU against
        ``bigdl.telemetry.peakTflops``) alongside the throughput line."""
        self._want_step_flops = False
        fn = self._raw_step_fn
        if fn is None or not hasattr(fn, "lower"):
            return
        try:
            def spec(x):
                if hasattr(x, "shape") and hasattr(x, "dtype"):
                    return jax.ShapeDtypeStruct(x.shape, x.dtype)
                return x

            specs = jax.tree_util.tree_map(spec, args)
            # cost-analysis lowering only — never compiled, so it stays
            # outside the executable cache
            self._step_flops = telemetry.step_flops(
                fn.lower(*specs))  # lint: allow(untracked-jit)
            if self._step_flops:
                logger.info("Fused step cost estimate: %.3f GFLOP/step",
                            self._step_flops / 1e9)
        except Exception as e:  # diagnostics must never fail a train step
            logger.debug("fused-step FLOPs estimate unavailable: %s", e)

    def _probe_step_flops(self, inputs, targets, hyper, rng) -> None:
        """One-shot driver-side FLOPs probe: trainers that can reproduce
        their step's full argument tuple install ``_cost_args_fn``; the
        others simply have no MFU estimate."""
        self._want_step_flops = False
        args_fn = getattr(self, "_cost_args_fn", None)
        if args_fn is not None:
            self._estimate_step_flops(args_fn(inputs, targets, hyper, rng))

    def _warmup_compiles(self, inputs, targets, hyper, rng) -> None:
        """The AOT warmup phase: compile — or warm-load from the
        persistent cache — the fused step for the first batch's
        signature BEFORE step 1 dispatches, in an explicit
        telemetry-spanned phase (``driver/compile_warmup``,
        ``Compile/warmup_ms``).  Every trace/load/compile inside runs
        under the compile watchdog (``bigdl.compile.timeoutSec``), so a
        wedged compile aborts with a diagnosed
        :class:`~bigdl_tpu.utils.compile_cache.CompileTimeoutError`
        that the retry loop treats like divergence — restore and retry
        — instead of hanging the driver.  Trainers that cannot
        reproduce their step's argument tuple (no ``_cost_args_fn``)
        simply compile at step 1 as before."""
        from bigdl_tpu.utils import compile_cache
        args_fn = getattr(self, "_cost_args_fn", None)
        step = self._step_fn
        target = getattr(step, "__wrapped__", step)
        if args_fn is None or not isinstance(target,
                                             compile_cache.CachedStep):
            return
        was_warm = target.warm
        with telemetry.span("driver/compile_warmup"):
            t0 = telemetry.clock_ns()
            target.warmup(*args_fn(inputs, targets, hyper, rng))
            warm_ms = (telemetry.clock_ns() - t0) / 1e6
        telemetry.gauge("Compile/warmup_ms").set(warm_ms)
        if not was_warm:
            logger.info(
                "Compile warmup complete in %.0f ms: fused step %r "
                "ready before step 1 (%d cache hit(s), %d fresh "
                "compile(s))", warm_ms, target.label, target.cache_hits,
                target.compiles)

    def _params_dead(self) -> bool:
        """True if any live model parameter buffer was donated-and-deleted
        by a partially-completed jitted step."""
        for leaf in jax.tree_util.tree_leaves(self.model._params):
            if getattr(leaf, "is_deleted", lambda: False)():
                return True
        return False

    def _restore_latest_checkpoint(self) -> bool:
        """Reload the newest VALID model.N/optimMethod.N snapshot into the
        live model/optim shells (reference ``DistriOptimizer.scala:766-788``
        hardened): uncommitted, checksum-failing, or pair-incomplete
        snapshots are skipped, and a snapshot that fails to deserialize
        falls back to the next-older one.  Returns False when there is
        nothing to restore (no checkpoint configured, or no valid
        snapshot written yet).

        Topology-elastic: the manager compares the snapshot's recorded
        saving topology against this trainer's (``_topology_meta``) —
        same topology restores as always; a changed one either reshards
        (``bigdl.elastic.reshardOnRestore``: the canonical host trees
        restored here are re-partitioned for the new mesh when the
        trainer next places its carries) or raises a structured
        ``TopologyMismatchError``.  The whole restore is timed into
        ``Elastic/restore_ms``."""
        if self.checkpoint is None:
            return False
        # drain the async writer first: an in-flight snapshot must either
        # be fully committed or definitively absent before the scan (its
        # errors are logged, not raised — we are already recovering)
        self.checkpoint.join(raise_errors=False)
        if jax.process_count() > 1:
            # every rank must scan the same committed set: the writer's
            # drain (above) happens-before any rank lists the store.
            # Like the trigger-decision symmetry _check_symmetric_config
            # enforces, multi-host retry assumes SYMMETRIC failure —
            # every rank fails the same iteration and enters restore
            # together.  The failure classes this subsystem introduces
            # hold that invariant by construction: data/step faults
            # surface identically on all ranks, divergence works off the
            # pmean'd loss, and writer-only save errors are allgathered
            # to every rank by _run_checkpoint before anyone raises.
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("bigdl_restore_scan")
        from bigdl_tpu.utils import elastic
        with elastic.timed("restore") as timer:
            loaded = self.checkpoint.manager.load_latest(
                expected_topology=self._topology_meta())
            if loaded is None:
                # nothing restorable: the empty directory scan is not a
                # restore — don't report its duration as one
                timer.cancel()
                return False
            loaded_model, loaded_optim, n = loaded
            self.model.params = loaded_model.params
            self.model.state = loaded_model.state
            if isinstance(self.model, Container):
                self.model._adopt()
            self.optim_method.state = loaded_optim.state
            self.optim_method.set_slots(loaded_optim._slots)
        # consumed (and cleared) by the trainers' slot-placement blocks
        # via _consume_elastic_resumed — only a restore that actually
        # crossed a topology change is a reshard; a same-topology retry
        # restore re-places onto the same mesh and must not be timed
        # (or barriered) as one, keeping the gauge consistent with the
        # Elastic/reshards counter
        self._elastic_resumed = (
            self.checkpoint.manager.last_restore_mode == "reshard")
        logger.info("Restored snapshot model.%d / optimMethod.%d", n, n)
        return True

    def _consume_elastic_resumed(self) -> bool:
        """True when the live optimizer slots came from a checkpoint
        restore that CROSSED a topology change
        (``_restore_latest_checkpoint`` with ``last_restore_mode ==
        "reshard"``) — the slot placement that follows is a
        topology-elastic reshard worth timing (``elastic.place_slots``).
        A same-topology restore or a second in-process ``optimize()``
        re-placing live slots is not one, and must neither overwrite
        ``Elastic/reshard_ms`` nor pay a startup barrier.  Clears the
        flag: one placement consumes one restore."""
        resumed = (self.optim_method._slots is not None and
                   getattr(self, "_elastic_resumed", False))
        self._elastic_resumed = False
        return resumed

    # -- shared driver loop (used by Local and Distri trainers) -----------

    def _drive(self, fetch_batch, run_step, reset_epoch, publish,
               epoch_size: int, integrity=None) -> Dict[str, Any]:
        """The per-iteration driver loop both trainers share (reference
        ``optim/DistriOptimizer.scala:141-344`` / ``LocalOptimizer.scala:78``):
        fetch, step, bookkeeping/logging, epoch rollover, trigger-gated
        validation + checkpoint.

        ``fetch_batch() -> (inputs, targets, batch_size)`` and
        ``run_step(inputs, targets, hyper, rng) -> loss`` (or
        ``-> (loss, aux)`` — a device-resident diagnostics pytree rides
        the dispatch pipeline next to the loss) close over the trainer's
        device-resident carries; ``publish()`` syncs those carries back
        into the model/optim shells — called only when a trigger fires
        (the reference's getModel runs only at checkpoints, ``:818``) and
        once at the end.  ``integrity`` is the trainer's
        :class:`~bigdl_tpu.integrity.DriverIntegrity`: it names the
        first non-finite leaf in the bad-step diagnostics, and at its
        cadence classifies the step's fingerprint verdicts (raising
        ``IntegrityError`` / ``ReplicaDesyncError`` into the retry
        loop).
        """
        self._check_symmetric_config()
        state = _initial_driver_state()
        # resume: continue the counters a restored OptimMethod carries
        # (reference Train drivers pass --stateSnapshot and the optim state's
        # epoch/evalCounter pick up where the snapshot left off)
        state["neval"] = self.optim_method.state.get("evalCounter", 0) + 1
        state["epoch"] = self.optim_method.state.get("epoch", 1)
        stochastic = self.model.is_stochastic()
        rng_counter = state["neval"] - 1
        wall_start = time.time()

        from bigdl_tpu.utils import config as _config

        # -- telemetry: arm the tracer if configured, name the driver lane,
        # and start a fresh per-run step account.  Per-run gauges from a
        # previous optimize() in this process are dropped so a run that no
        # longer produces them cannot re-chart stale values.
        telemetry.maybe_arm_from_config()
        if telemetry.tracing_enabled():
            telemetry.name_thread("driver")
            # per-run timeline: a second optimize() in this process must
            # export only its own spans (rings stay registered, events
            # and the trace epoch reset)
            telemetry.reset_tracer()
        telemetry.REGISTRY.drop_prefix("Telemetry/")
        telemetry.REGISTRY.drop_prefix("Analysis/")
        step_account = telemetry.StepAccount(
            window=_config.get_int("bigdl.telemetry.percentileWindow", 512),
            detector=telemetry.SlowStepDetector(
                _config.get_float("bigdl.telemetry.slowStepFactor", 0.0),
                warmup=_config.get_int("bigdl.telemetry.slowStepWarmup", 5),
                cooldown=_config.get_int("bigdl.telemetry.slowStepCooldown",
                                         50)))
        self._step_account = step_account
        log_every = max(1, _config.get_int("bigdl.telemetry.logEveryN", 1))
        slow_profile_dir = _config.get_property(
            "bigdl.telemetry.profileOnSlowStep")
        #: one-shot jax.profiler capture requested by the slow-step detector
        slow_req = {"due": False, "captured": False}
        self._want_step_flops = (_config.get_bool("bigdl.telemetry.mfu",
                                                  False)
                                 and self._step_flops is None)
        peak_tflops = _config.get_float("bigdl.telemetry.peakTflops", 0.0)

        # Dispatch pipeline: iteration i's loss is read (a blocking device
        # round-trip — expensive when the chip sits behind a network
        # tunnel) only after up to ``bigdl.pipeline.depth`` further
        # iterations are queued, with the device→host copy started
        # asynchronously at dispatch.  Measured on the tunneled v5e:
        # per-iteration wall time 92 ms serialized → 13 ms at depth 8 for
        # a small step.  Every iteration still gets its reference-protocol
        # log line — it just prints up to `depth` dispatches later, and
        # always before any sync point (validation, checkpoint, end).
        # Loss-reading end triggers (min_loss) set Trigger.reads_loss, and
        # the loop flushes before evaluating them so they never see a
        # stale loss — effectively depth=1 while such a trigger is
        # installed (the user chose stop-on-loss semantics over latency
        # hiding).
        max_bad_steps = _config.get_int("bigdl.divergence.maxBadSteps", 5)

        from bigdl_tpu.analysis.hostsync import host_pull

        def drain(item, nxt):
            loss_dev, bsz, t0, epoch, recs, neval, parts, aux = item
            # the ONE intended device→host pull of the hot loop, through
            # the explicit choke point (permitted while the guard is armed)
            with telemetry.span("driver/host_wait"):
                t_pull = telemetry.clock_ns()
                loss = float(host_pull(loss_dev, what="iteration loss"))
                pull_ns = telemetry.clock_ns() - t_pull
            t_book = telemetry.clock_ns()
            # per-iteration wall time = interval to the NEXT dispatch (the
            # flush happens up to depth-1 dispatches later, so "now - t0"
            # would overstate it depth-fold)
            next_t0 = nxt[2] if nxt is not None else telemetry.clock_ns()
            dt = max(next_t0 - t0, 1)
            self.metrics.add("computing time for each node", dt)
            state["Loss"] = loss
            throughput = bsz / max(dt / 1e9, 1e-9)
            mfu_note = ""
            if self._step_flops:
                tflops = self._step_flops / max(dt / 1e9, 1e-9) / 1e12
                telemetry.gauge("Telemetry/tflops", summary=True).set(tflops)
                if peak_tflops > 0:
                    telemetry.gauge("Telemetry/mfu", summary=True).set(
                        tflops / peak_tflops)
                    mfu_note = (f" MFU is "
                                f"{100 * tflops / peak_tflops:.1f}%.")
                else:
                    mfu_note = f" Achieved {tflops:.3f} TFLOP/s."
            # bigdl.telemetry.logEveryN rate-limits the per-iteration log
            # line (default 1 = the reference protocol, unchanged); the
            # skipped path formats nothing
            if neval % log_every == 0:
                logger.info(
                    "[Epoch %d %d/%d][Iteration %d] Train %d in %.4f "
                    "seconds. Throughput is %.1f records/second. Loss is "
                    "%.6f.%s",
                    epoch, recs, epoch_size, neval, bsz, dt / 1e9,
                    throughput, loss, mfu_note)
            # divergence guard, host side: the in-step guard already kept
            # the params/slots/state carries at their pre-step values, so
            # a bad step costs one wasted iteration, not a poisoned model;
            # here we count consecutive bad steps and escalate to a
            # restore-from-snapshot once a transient numeric blip looks
            # like a genuinely diverged trajectory
            if not math.isfinite(loss):
                state["consecutiveBadSteps"] += 1
                # diagnosed divergence: the step recorded the index of
                # the first non-finite leaf on device; name it (the pull
                # is explicit, through the choke point, and happens only
                # on the already-slow bad-step path)
                culprit = ""
                if (integrity is not None and aux is not None
                        and "nf" in aux):
                    culprit = integrity.describe_nonfinite(
                        int(host_pull(aux["nf"],
                                      what="first non-finite leaf")))
                logger.warning(
                    "Non-finite loss/grads (%s) at iteration %d — update "
                    "skipped (%d consecutive bad step(s); restore after "
                    "%d)%s", loss, neval, state["consecutiveBadSteps"],
                    max_bad_steps, culprit)
                if 0 < max_bad_steps <= state["consecutiveBadSteps"]:
                    incident.record(
                        "optim/divergence", iteration=neval,
                        bad_steps=state["consecutiveBadSteps"])
                    raise DivergenceError(
                        f"{state['consecutiveBadSteps']} consecutive "
                        f"non-finite losses (last at iteration {neval}) — "
                        "restoring the latest valid snapshot"
                        f"{culprit}")
            else:
                state["consecutiveBadSteps"] = 0
            # training-state integrity: classify the fingerprint
            # verdicts at the configured cadence — cross-replica
            # disagreement / continuity breaks raise into the retry
            # loop, healthy verdicts feed the weight-health gates
            if (integrity is not None and aux is not None
                    and "cont" in aux and integrity.due(neval)):
                with telemetry.span("driver/integrity_check"):
                    integrity.check(aux, neval)
            # step-time decomposition: data-wait / compute / host-pull /
            # bookkeeping measured, the signed residual is unaccounted —
            # the five always sum to the wall interval exactly.  The wall
            # interval t0(i) -> t0(i+1) contains THIS iteration's dispatch
            # and bookkeeping but the NEXT iteration's fetch, so the
            # data-wait share comes from the next item's measured parts
            # (a stalled fetch lands on the same interval whose wall time
            # it inflated); the final flushed interval contains no fetch.
            data_ns = nxt[6][0] if nxt is not None else 0.0
            fired = step_account.account(
                dt, data_wait=data_ns, compute=parts[1], host_pull=pull_ns,
                bookkeeping=parts[2] + (telemetry.clock_ns() - t_book))
            if fired:
                telemetry.instant("driver/slow_step", iteration=neval,
                                  step_ms=round(dt / 1e6, 3))
                logger.warning(
                    "Slow step at iteration %d: %.1f ms (> %.1f ms = "
                    "k x EMA); %d anomaly window(s) this run", neval,
                    dt / 1e6, step_account.detector.threshold() / 1e6,
                    step_account.detector.fired)
                if slow_profile_dir:
                    # every process requests its own (process-local)
                    # profiler capture — like the scheduled window, one
                    # capture per host; only the timeline dump is a
                    # single-writer artifact
                    slow_req["due"] = True
                    if is_writer_process() and telemetry.tracing_enabled():
                        # bounded (bigdl.telemetry.maxTimelineDumps,
                        # oldest-first eviction) and disk-full-guarded: a
                        # flapping detector must not fill the disk with
                        # dump files, nor crash on one already full
                        _resource_storage.bounded_timeline_export(
                            os.path.join(
                                str(slow_profile_dir),
                                f"slowstep_{neval}_timeline.json"))
            with telemetry.span("driver/summary"):
                self._summarize_train(loss, throughput, neval)

        pipeline = DispatchPipeline(drain)
        flush_pending = pipeline.flush
        end_reads_loss = getattr(self.end_when, "reads_loss", False)

        # hung-step watchdog (bigdl.watchdog.stallFactor): a monitor
        # thread fed one heartbeat per iteration; a step whose OPEN
        # interval exceeds k x the completed-step EMA dumps the telemetry
        # timeline and aborts this thread with HungStepError so the retry
        # loop restores — instead of the job hanging forever.  Legitimate
        # long phases (publish/validation/checkpoint) run under paused().
        from contextlib import nullcontext
        from bigdl_tpu.utils import elastic as _elastic
        watchdog = _elastic.HungStepWatchdog.from_config()
        wd_pause = (watchdog.paused if watchdog is not None
                    else nullcontext)

        def should_end():
            if end_reads_loss:
                flush_pending()
            return self.end_when(state)

        # batch prefetch: the host->device transfer inside fetch_batch is
        # a tunnel round-trip — run it ahead on a producer thread.  The
        # PRODUCER owns the dataset end to end: it counts records and
        # performs the epoch rollover + reshuffle at the boundary
        # (reference DistriOptimizer:333-344), so iterators and index
        # arrays are single-threaded and the batch sequence is
        # deterministic regardless of how far ahead the producer runs —
        # the consumer below tracks epochs independently for state/
        # logging from the same bsz stream, so the two stay in lockstep.
        # bigdl.prefetch.depth=0 restores synchronous fetching.
        from bigdl_tpu.engine import BatchPrefetcher
        fetched = {"records": 0}

        def on_batch(batch):
            fetched["records"] += batch[2]
            if fetched["records"] >= epoch_size:
                fetched["records"] = 0
                reset_epoch()

        # host-sync sanitizer (analysis pass 2): implicit device→host pulls
        # inside the fetch→step→dispatch region fail with their call-site
        # (strict) or log-once-and-count (warn).  The host-driven feval
        # path (LBFGS line search) pulls by design and is exempt.
        from bigdl_tpu.analysis.hostsync import NULL_GUARD, HostSyncGuard
        if getattr(self.optim_method, "requires_feval", False):
            hot_guard = NULL_GUARD
        else:
            hot_guard = HostSyncGuard.from_config()
        # bigdl.analysis.hotLoopScope: "iteration" sanitizes fetch+step,
        # "step" only the dispatch region (for exotic fetch transformers
        # that pull device values by design)
        scope = str(_config.get_property("bigdl.analysis.hotLoopScope",
                                         "iteration"))
        fetch_guard = hot_guard if scope == "iteration" else NULL_GUARD
        # per-run baseline: the global sync counter survives across runs
        # in one process; TrainSummary must chart THIS run's syncs
        if hot_guard.enabled:
            from bigdl_tpu.analysis.hostsync import STATS as _hs_stats
            self._hostsync_base = _hs_stats.snapshot()["implicit"]
        else:
            self._hostsync_base = None
        # the guard's hooks are thread-local: the producer thread runs the
        # actual fetch under bigdl.prefetch.depth > 0, so the prefetcher
        # arms the fetch guard AT the fetch call site (the in-loop arming
        # below covers only the synchronous depth=0 path and the dequeue)
        fetch = BatchPrefetcher(
            fetch_batch, on_batch=on_batch,
            guard=fetch_guard if fetch_guard.enabled else None)
        #: the AOT compile-warmup phase runs once, at the first iteration
        warmed = {"done": False}
        profiling = False
        profiled = False   # the window fires once, even across resumes

        def stop_profile():
            nonlocal profiling
            if profiling:
                profiling = False
                try:
                    # flush first so the traced iterations' device work
                    # (all dispatched asynchronously) completes inside the
                    # window...
                    flush_pending()
                finally:
                    # ...but a poisoned queue re-raising must STILL close
                    # the global profiler session, or the retry loop's
                    # next start_trace aborts on 'already running'
                    jax.profiler.stop_trace()
                logger.info("Profiler trace written to %s",
                            self._profile_dir)
                # the request is consumed: a SECOND optimize() on this
                # Optimizer must not silently re-capture into the same
                # log_dir and mix xplane artifacts — callers wanting
                # another window call set_trace_profile again
                self._profile_dir = None

        # started HERE, not at construction: everything between would-be
        # start and this try can raise, and only the finally below joins
        # the monitor — a retried setup failure must not leak a polling
        # thread per attempt
        if watchdog is not None:
            watchdog.start()
        try:
            while not should_end():
                # >= not ==: a run resumed past the start iteration still
                # captures (once) instead of silently skipping the window
                if (self._profile_dir and not profiled and
                        state["neval"] >= self._profile_start):
                    pdir = self._profile_dir
                    if jax.process_count() > 1:   # one capture per host
                        pdir = os.path.join(
                            pdir, f"process_{jax.process_index()}")
                    jax.profiler.start_trace(pdir)
                    profiling = profiled = True
                    profile_end = state["neval"] + self._profile_n
                if (slow_req["due"] and not slow_req["captured"] and
                        not profiling and not self._profile_dir):
                    # on-demand capture requested by the slow-step
                    # detector: one jax.profiler window over the next
                    # iteration (once per run; a user-scheduled
                    # set_trace_profile window always wins the session)
                    slow_req["due"] = False
                    slow_req["captured"] = True
                    pdir = os.path.join(str(slow_profile_dir),
                                        "slowstep_profile")
                    if jax.process_count() > 1:
                        pdir = os.path.join(
                            pdir, f"process_{jax.process_index()}")
                    self._profile_dir = pdir
                    jax.profiler.start_trace(pdir)
                    profiling = True
                    profile_end = state["neval"] + 1
                if watchdog is not None:
                    watchdog.heartbeat()
                # host-memory governor: one poll per iteration rolls up
                # every registered buffer account against the soft budget
                # (bigdl.resources.hostMemBudgetMB) and fires the
                # registered shrinkers edge-triggered under pressure
                _governor.poll()
                if _chaos.active():
                    # chaos harness step-level hooks: a simulated step
                    # failure raises here (the retry loop absorbs it), a
                    # preemption injection sets the elastic flag checked
                    # below, a stall blocks to exercise the watchdog, and
                    # a nan-loss injection flags this iteration's loss
                    inject_nan = _chaos.on_step(state["neval"])
                else:
                    inject_nan = False
                if _elastic.preemption_requested():
                    # graceful drain (SIGTERM/SIGINT via PreemptionHandler,
                    # or bigdl.chaos.preemptAt): finish the in-flight
                    # dispatches, publish the carries so the shells hold
                    # the newest weights, and unwind as Preempted — the
                    # retry loop commits the grace-period snapshot +
                    # resumable marker and exits instead of retrying.
                    # The counter is bumped HERE, not in the signal
                    # handler (registry locks are not signal-safe); the
                    # drain runs watchdog-paused — a long publish during
                    # the grace window is not a hung step.
                    telemetry.counter(
                        "Elastic/preemptions",
                        help="graceful-shutdown drains observed").inc()
                    with wd_pause():
                        flush_pending()
                        with telemetry.span("driver/publish"):
                            publish()
                    raise _elastic.Preempted(
                        f"preemption requested "
                        f"({_elastic.preemption_reason()}) — drained and "
                        f"published at iteration {state['neval']}")
                with fetch_guard.armed():
                    with telemetry.span("driver/fetch"):
                        t_data = telemetry.clock_ns()
                        inputs, targets, bsz = fetch()
                        data_wait_ns = telemetry.clock_ns() - t_data
                    self.metrics.add("get batch time", data_wait_ns)

                with hot_guard.armed():
                    self.optim_method.state["epoch"] = state["epoch"]
                    hyper = self.optim_method.hyper()
                    rng = (jax.random.PRNGKey(rng_counter) if stochastic else
                           jax.random.PRNGKey(0))
                    rng_counter += 1

                    if not warmed["done"]:
                        # AOT warmup: the fused step is compiled (or
                        # cache-loaded) HERE, supervised and spanned, so
                        # the dispatch below is a device step — never an
                        # unguarded 15-45 s implicit compile
                        warmed["done"] = True
                        self._warmup_compiles(inputs, targets, hyper, rng)
                    if self._want_step_flops:
                        self._probe_step_flops(inputs, targets, hyper, rng)
                    t0 = telemetry.clock_ns()
                    with telemetry.span("driver/device_step"):
                        out = run_step(inputs, targets, hyper, rng)
                        loss_dev, step_aux = (
                            out if isinstance(out, tuple) else (out, None))
                        dispatch_ns = telemetry.clock_ns() - t0
                    if inject_nan:
                        loss_dev = float("nan")
                    t_book = telemetry.clock_ns()
                    self.optim_method.step_done()
                    # decomposition parts measured at dispatch time; the
                    # drain adds its own host-pull/bookkeeping shares when
                    # the interval retires
                    parts = (data_wait_ns, dispatch_ns,
                             telemetry.clock_ns() - t_book)
                    pipeline.push(loss_dev, bsz, t0, state["epoch"],
                                  state["recordsProcessedThisEpoch"] + bsz,
                                  state["neval"], parts, step_aux)

                state["recordsProcessedThisEpoch"] += bsz

                # epoch accounting only — the rollover itself (reshuffle,
                # iterator reset) already happened on the producer at this
                # exact record boundary
                if state["recordsProcessedThisEpoch"] >= epoch_size:
                    state["epoch"] += 1
                    state["recordsProcessedThisEpoch"] = 0

                state["neval"] += 1
                if profiling and state["neval"] >= profile_end:
                    stop_profile()
                # keep the snapshot's epoch current across the rollover so
                # a resumed run continues at the right epoch
                self.optim_method.state["epoch"] = state["epoch"]

                v_due = self._validation_due(state)
                c_due = self._checkpoint_due(state)
                p_due = (self.train_summary is not None and
                         getattr(self.train_summary, "save_parameters_due",
                                 lambda s: False)(state))
                if v_due or c_due or p_due:
                    # a checkpoint or validation pass can legitimately
                    # dwarf a training step — not a stall
                    with wd_pause():
                        flush_pending()   # ordered log lines pre-validation
                        with telemetry.span("driver/publish"):
                            publish()
                        if v_due:
                            with telemetry.span("driver/validation"):
                                self._run_validation(state)
                        if c_due:
                            with telemetry.span("driver/checkpoint"):
                                self._run_checkpoint(state)
                        if p_due and is_writer_process():
                            # weight histograms (reference
                            # DistriOptimizer:426-456); the due-decision is
                            # shared (all processes publish), the write is
                            # not
                            with telemetry.span("driver/param_histograms"):
                                self.train_summary.save_parameters(
                                    self.model, state["neval"] - 1)
        finally:
            # the watchdog goes down FIRST: stop_profile()'s flush can
            # block for several EMAs of queued dispatches, and an armed
            # monitor would read that (or the post-loop flush/publish) as
            # a hung step and abort a COMPLETING run into a pointless
            # restore-and-retrain.  The trace must still close even if
            # the flush re-raises — an unterminated xplane capture is
            # unreadable — and the producer thread must stop regardless.
            try:
                if watchdog is not None:
                    watchdog.stop()
            finally:
                try:
                    stop_profile()
                finally:
                    fetch.stop()

        flush_pending()
        publish()
        # input-pipeline accounting: where the prefetch stages spent their
        # time (fetch vs blocking uploads device-resident), plus per-stage
        # ingest counters when a StreamingIngest engine fed the run — the
        # numbers that say whether a slow run was input-bound
        if fetch.batches:
            self.metrics.add("batch fetch time", fetch.fetch_ns)
            self.metrics.add("transfer block time", fetch.block_ns)
        from bigdl_tpu.dataset import ingest as _ingest
        for eng in sorted((e for e in _ingest._LIVE if e.has_active_run()),
                          key=lambda e: e.name):
            for stage, snap in eng.stats().items():
                logger.info(
                    "Ingest %s stage %s: %d items, %.1f/s, busy %.1fs, "
                    "starve %.1fs, backpressure %.1fs, workers %d",
                    eng.name, stage,
                    snap["items"], snap["throughput_per_sec"],
                    snap["busy_s"], snap["starve_s"],
                    snap["backpressure_s"],
                    eng.stage_workers.get(stage, 1))
            ups, downs = (eng.autoscale_events["up"],
                          eng.autoscale_events["down"])
            if ups or downs:
                logger.info(
                    "Ingest %s autoscaler: %d scale-up(s), %d "
                    "scale-down(s), final decode workers %d", eng.name,
                    ups, downs, eng.stage_workers["decode"])
            if eng.epoch_cache is not None:
                cache = eng.epoch_cache.stats()
                logger.info(
                    "Ingest %s epoch cache: %d hit(s), %d miss(es), "
                    "%d RAM + %d disk segment(s), %.1f MB RAM, "
                    "%d corrupt, %d evicted", eng.name, cache["hits"],
                    cache["misses"], cache["ram_segments"],
                    cache["disk_segments"], cache["ram_bytes"] / 2 ** 20,
                    cache["corrupt_segments"], cache["evicted_segments"])
        # where the step time went, one line (the full series is in the
        # Telemetry/* scalars and the telemetry.json snapshot)
        acct = step_account.summary()
        if acct.get("steps"):
            logger.info(
                "Step time decomposition over %d steps (mean %.1f ms): "
                "data-wait %.0f%%, compute %.0f%%, host-pull %.0f%%, "
                "bookkeeping %.0f%%, unaccounted %.0f%%; p50/p95/p99 "
                "%.1f/%.1f/%.1f ms; %d slow step(s)",
                acct["steps"], acct["mean_step_ms"],
                100 * acct["data_wait_frac"], 100 * acct["compute_frac"],
                100 * acct["host_pull_frac"],
                100 * acct["bookkeeping_frac"],
                100 * acct["unaccounted_frac"], acct.get("p50_ms", 0.0),
                acct.get("p95_ms", 0.0), acct.get("p99_ms", 0.0),
                acct["slow_steps"])
        self._export_telemetry(step_account)
        logger.info("Training finished in %.1f s.", time.time() - wall_start)
        return state

    def _export_telemetry(self, step_account) -> None:
        """End-of-run telemetry artifacts (writer process only): the
        Chrome trace timeline (``bigdl.telemetry.tracePath``) and the
        registry snapshot (``bigdl.telemetry.snapshotPath`` — a directory
        gets ``telemetry.json`` inside it)."""
        from bigdl_tpu.utils import config as _config
        if not is_writer_process():
            return
        # both exports run disk-full-guarded: a full disk disables the
        # artifact for the rest of the run with ONE structured warning
        # (Resources/storage_degraded) — it never fails the training run
        trace_path = _config.get_property("bigdl.telemetry.tracePath")
        if trace_path and telemetry.tracing_enabled():
            if _resource_storage.guarded_export(
                    "telemetry",
                    lambda: telemetry.export_chrome_trace(str(trace_path))):
                logger.info("Telemetry timeline written to %s", trace_path)
        snap_path = _config.get_property("bigdl.telemetry.snapshotPath")
        if snap_path:
            import json
            snap_path = str(snap_path)
            if os.path.isdir(snap_path):
                snap_path = os.path.join(snap_path, "telemetry.json")
            snap = telemetry.REGISTRY.snapshot()
            snap["step_summary"] = step_account.summary()

            def _write_snap():
                with open(snap_path, "w") as f:
                    json.dump(snap, f, indent=1, sort_keys=True)

            if _resource_storage.guarded_export("telemetry", _write_snap):
                logger.info("Telemetry snapshot written to %s", snap_path)

    def _check_symmetric_config(self) -> None:
        """Multi-host guard: the publish/validation sync points contain
        collectives, and whether they run is decided from per-process
        configuration.  A user who configures a checkpoint, summary, or
        validation on only SOME processes (a natural misreading of the
        single-writer discipline — the gating happens at write time, not
        at configuration time) would send the processes down different
        collective sequences and hang the job with no diagnostic.  Catch
        it up front with a host allgather of the configuration shape."""
        if jax.process_count() <= 1:
            return
        from jax.experimental import multihost_utils
        ts = self.train_summary
        has_param_hist = (ts is not None and
                          getattr(ts, "get_summary_trigger",
                                  lambda n: None)("Parameters") is not None)
        flags = np.array(
            [self.checkpoint is not None,
             ts is not None,
             has_param_hist,
             self.validation_trigger is not None,
             self.validation_summary is not None],
            dtype=np.int32)
        gathered = np.asarray(multihost_utils.process_allgather(flags))
        if not (gathered == flags[None, :]).all():
            raise ValueError(
                "training configuration differs across processes "
                f"(per-process [checkpoint, train_summary, param_histograms, "
                f"validation, validation_summary] flags:\n{gathered}) — "
                "every process must configure the same checkpoint/summary/"
                "validation setup; only the WRITES are limited to process 0 "
                "(bigdl_tpu.optim.optimizer.is_writer_process)")

    def _publish(self, params, slots, mstate) -> None:
        """Sync the jitted-loop carries back into the stateful shell so
        validation/checkpoint/users see current values."""
        self.model.params = params
        self.model.state = mstate
        if isinstance(self.model, Container):
            self.model._adopt()
        self.optim_method.set_slots(slots)

    def _validation_due(self, state) -> bool:
        return (self.validation_trigger is not None and
                self.validation_dataset is not None and
                self.validation_trigger(state))

    def _eval_mesh(self):
        """Mesh for sharded validation forwards; the distributed trainer
        overrides this with its training mesh."""
        return None

    def _run_validation(self, state) -> None:
        from bigdl_tpu.optim.evaluator import evaluate_dataset
        results = evaluate_dataset(self.model, self.validation_dataset,
                                   self.validation_methods,
                                   mesh=self._eval_mesh())
        for method, res in results:
            logger.info("%s is %s", method.name, res)
            state["score"] = res.final_result()
            self.optim_method.state["score"] = res.final_result()
            if self.validation_summary is not None and is_writer_process():
                self.validation_summary.add_scalar(
                    method.name, res.final_result(), state["neval"] - 1)

    def _checkpoint_due(self, state) -> bool:
        return self.checkpoint is not None and self.checkpoint.trigger(state)

    def _run_checkpoint(self, state) -> None:
        # every process reaches this point (the trigger decision is
        # shared), but only the writer touches the filesystem; the sync
        # afterwards keeps non-writers from racing ahead into a restore
        # (or a crash-retry) that would read a half-finished snapshot
        # set.  A save failure is inherently WRITER-ONLY — re-raising it
        # on rank 0 alone would send that rank into the retry loop's
        # restore barrier while its peers sit at this checkpoint sync,
        # mispairing the collectives — so the error is withheld until
        # after an allgathered failure flag lets EVERY rank raise
        # symmetrically and enter restore together.
        err: Optional[BaseException] = None
        if is_writer_process():
            try:
                self.checkpoint.save(self.model, self.optim_method,
                                     state["neval"] - 1,
                                     topology=self._topology_meta())
            except BaseException as e:  # noqa: BLE001 — re-raised below
                err = e
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            flag = np.array([0 if err is None else 1], np.int32)
            # the allgather doubles as the checkpoint barrier
            gathered = np.asarray(multihost_utils.process_allgather(flag))
            if gathered.any():
                # EVERY rank must raise the SAME retryable class: the
                # writer re-raising the original (which may be a
                # non-retryable TypeError, e.g. an unpicklable model
                # attribute) while peers raise RuntimeError would kill
                # rank 0 instantly and hang the others at the restore
                # barrier.  A persistent save failure still dies cleanly
                # — symmetrically, once the retry budget is spent.
                raise RuntimeError(
                    "checkpoint write failed on the writer process "
                    "(rank 0) — restoring the latest valid snapshot on "
                    "every rank") from err
        if err is not None:
            raise err

    def _summarize_train(self, loss: float, throughput: float,
                         neval: int) -> None:
        if self.train_summary is None or not is_writer_process():
            return
        self.train_summary.add_scalar("Loss", loss, neval)
        self.train_summary.add_scalar("Throughput", throughput, neval)
        self.train_summary.add_scalar(
            "LearningRate", self.optim_method.get_learning_rate(), neval)
        # sanitizer counters route through the telemetry registry with
        # their historical tags: post-warmup retraces of the fused step
        # and implicit host syncs caught in the hot loop THIS RUN — a
        # healthy run charts both flat at zero.  Independent gates:
        # either pass can be off while the other still reports.
        if self._retrace_sentinel is not None:
            telemetry.gauge("Analysis/retraces", summary=True).set(
                self._retrace_sentinel.retraces)
        if getattr(self, "_hostsync_base", None) is not None:
            from bigdl_tpu.analysis.hostsync import STATS as _hs_stats
            telemetry.gauge("Analysis/implicit_host_syncs",
                            summary=True).set(
                _hs_stats.snapshot()["implicit"] - self._hostsync_base)
        # THE one emission loop: every summary-flagged registry metric
        # (Analysis/* above, the Telemetry/* decomposition gauges) plus
        # every registered provider (the streaming-ingest engine's
        # per-stage Ingest/* scalars) — one naming scheme, one flush path
        scalars = telemetry.summary_scalars()
        acct = self._step_account
        if acct is not None and acct.steps:
            scalars += acct.percentile_scalars()
        for tag, value in scalars:
            self.train_summary.add_scalar(tag, value, neval)

    # -- factory ----------------------------------------------------------

    @staticmethod
    def create(model: Module, dataset, criterion: Criterion,
               batch_size: Optional[int] = None) -> "Optimizer":
        """(reference ``Optimizer.apply:268``) — list/LocalDataSet →
        LocalOptimizer; ShardedDataSet → DistriOptimizer."""
        if isinstance(dataset, (list, tuple)):
            dataset = LocalDataSet(dataset)
        if batch_size is not None and not _yields_minibatches(dataset):
            from bigdl_tpu.dataset.transformer import SampleToMiniBatch
            pn = dataset.partition_num if isinstance(dataset, ShardedDataSet) else 1
            dataset = dataset.transform(SampleToMiniBatch(batch_size, pn))
        if isinstance(dataset, ShardedDataSet):
            try:
                from bigdl_tpu.parallel.distri_optimizer import DistriOptimizer
            except ImportError as e:
                raise NotImplementedError(
                    "the distributed trainer (bigdl_tpu.parallel."
                    "distri_optimizer) is not available in this build") from e
            return DistriOptimizer(model, dataset, criterion)
        return LocalOptimizer(model, dataset, criterion)


def _yields_minibatches(ds: AbstractDataSet) -> bool:
    from bigdl_tpu.dataset.transformer import ChainedTransformer, SampleToMiniBatch

    def has_batcher(t) -> bool:
        if isinstance(t, SampleToMiniBatch):
            return True
        if isinstance(t, ChainedTransformer):
            return any(has_batcher(s) for s in t.stages)
        return False

    ts = getattr(ds, "transformers", None)
    if ts is None and isinstance(ds, ShardedDataSet):
        # any local shard: every shard carries the same transformer chain
        ts = next(iter(ds.shards.values())).transformers
    return bool(ts) and any(has_batcher(t) for t in ts)


# shared state-key conventions (reference DistriOptimizer driverState)
def _initial_driver_state() -> Dict[str, Any]:
    return {"epoch": 1, "neval": 1, "Loss": None, "score": None,
            "recordsProcessedThisEpoch": 0, "consecutiveBadSteps": 0}


class LocalOptimizer(Optimizer):
    """Single-process trainer (reference ``optim/LocalOptimizer.scala:41``).

    One fused jitted step per iteration: forward, loss (+ regularizers),
    backward, and the optimizer's pure update all inside XLA.  Dynamic
    hyper-parameters (decayed lr, step count) enter as scalar arguments so
    the step never retraces.
    """

    def _build_step(self):
        model, criterion = self.model, self.criterion
        optim = self.optim_method
        if getattr(optim, "requires_feval", False):
            if self.precision is not None:
                raise ValueError(
                    f"{type(optim).__name__} uses the host-driven feval "
                    "path, which is fp32-only; unset set_precision")
            return self._build_feval_step()

        precision = self.precision
        aux_weight = self.moe_aux_weight
        from bigdl_tpu.utils import config
        from bigdl_tpu import integrity as _integrity
        from bigdl_tpu.resources import microbatch as _microbatch
        guard = config.get_bool("bigdl.divergence.guard", True)
        every_n = config.get_int("bigdl.integrity.everyN", 0)
        fp_seed = config.get_int("bigdl.integrity.seed",
                                 _integrity.DEFAULT_SEED)
        #: OOM re-plan: > 1 splits the batch into mb_k accumulation
        #: chunks inside ONE fused program (resources.microbatch)
        mb_k = max(1, int(self._microbatch_k))

        def _step_core(params, slots, mstate, inputs, targets, hyper, rng,
                       fpc=None, tick=None):
            def loss_fn(p):
                out, new_mstate = mixed_precision_forward(
                    model, p, inputs, mstate, precision, True, rng)
                loss = criterion.apply(out, targets)
                loss = loss + regularization_penalty(model, p)
                loss = loss + moe_aux_penalty(model, new_mstate, aux_weight)
                return loss, new_mstate

            if mb_k > 1:
                # microbatch re-plan: k forward/backward passes over B/k
                # samples each, Kahan-compensated mean of (loss, grads,
                # state) — mean of equal-chunk means IS the full-batch
                # mean, so the numerics stay allclose to the full-batch
                # step while peak activation memory drops ~k-fold.  One
                # lax.scan keeps it a single fused program.
                def chunk_grads(xs):
                    cin, ctg = xs

                    def chunk_loss(p):
                        out, nm = mixed_precision_forward(
                            model, p, cin, mstate, precision, True, rng)
                        closs = criterion.apply(out, ctg)
                        closs = closs + regularization_penalty(model, p)
                        closs = closs + moe_aux_penalty(model, nm,
                                                        aux_weight)
                        return closs, nm

                    (closs, nm), cg = jax.value_and_grad(
                        chunk_loss, has_aux=True)(params)
                    return closs, cg, nm

                loss, grads, new_mstate = _microbatch.scan_mean(
                    chunk_grads, (inputs, targets), mb_k)
            else:
                (loss, new_mstate), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
            new_params, new_slots = optim.pure_update(grads, params, slots,
                                                      hyper)
            aux: Dict[str, Any] = {}
            ok = None
            if guard:
                # divergence guard: a non-finite loss/grad step keeps
                # every carry at its pre-step value.  The returned loss is
                # poisoned to NaN whenever the step was skipped — a
                # non-finite GRADIENT under a finite loss must still reach
                # the driver's bad-step counter, or a permanently
                # overflowing backward would freeze training silently.
                # ``nf`` names the first non-finite leaf for the driver's
                # diagnosed log line / DivergenceError.
                ok, nf = _integrity.first_nonfinite(loss, grads)
                aux["nf"] = nf
            if fpc is not None:
                # integrity: input fingerprints vs the previous step's
                # output carry — state that changed outside the fused
                # step is silent corruption; the verdict joins the
                # update-skip guard so a corrupt run FREEZES (restorable)
                # instead of training on rotten weights
                fp_p_in = _integrity.fingerprint_tree(params, fp_seed)
                fp_s_in = _integrity.fingerprint_tree(
                    slots, fp_seed + _integrity.SLOT_SEED_OFF)
                cont_ok, latch, bad_iter = _integrity.continuity_check(
                    fpc, fp_p_in, fp_s_in, tick)
                intact = latch == 0
                ok = intact if ok is None else jnp.logical_and(ok, intact)
            if ok is not None and ok is not True:
                new_params = select_tree(ok, new_params, params)
                new_slots = select_tree(ok, new_slots, slots)
                new_mstate = select_tree(ok, new_mstate, mstate)
            if guard:
                loss = jnp.where(aux["nf"] == _integrity.NF_SENTINEL,
                                 loss, jnp.nan)
            if fpc is not None:
                fp_p_out = _integrity.fingerprint_tree(new_params, fp_seed)
                fp_s_out = _integrity.fingerprint_tree(
                    new_slots, fp_seed + _integrity.SLOT_SEED_OFF)
                fp_g = _integrity.fingerprint_tree(
                    grads, fp_seed + _integrity.GRAD_SEED_OFF)
                aux.update(
                    cont=latch, bad_iter=bad_iter, fp_p=fp_p_out,
                    fp_s=fp_s_out, fp_g=fp_g,
                    pn=_integrity.sq_norm(new_params),
                    un=_integrity.sq_norm_diff(new_params, params),
                    gn=_integrity.sq_norm(grads),
                    fpc=_integrity.pack_carry(latch, bad_iter, fp_p_out,
                                              fp_s_out))
            return new_params, new_slots, new_mstate, loss, aux

        if every_n > 0:
            def step(params, slots, mstate, inputs, targets, hyper, rng,
                     fpc, tick):
                return _step_core(params, slots, mstate, inputs, targets,
                                  hyper, rng, fpc, tick)
        else:
            def step(params, slots, mstate, inputs, targets, hyper, rng):
                return _step_core(params, slots, mstate, inputs, targets,
                                  hyper, rng)

        from bigdl_tpu.analysis import program_contracts
        from bigdl_tpu.utils import compile_cache
        # the re-planned program gets its own label: same argument
        # signature, DIFFERENT traced body — it must never collide with
        # the full-batch executable in the compile cache
        label = "local" if mb_k == 1 else f"local_mb{mb_k}"
        return compile_cache.tracked_jit(
            step, label=label, topology=self._topology_meta(),
            contract=program_contracts.local_contract(precision),
            donate_argnums=(0, 1, 2))

    def _build_feval_step(self):
        """Host-driven step for multi-evaluation methods (LBFGS line
        search): one jitted loss+grad function, called repeatedly by the
        method's own inner loop.  Module state (BatchNorm statistics) is
        held fixed within a step — LBFGS is a full-batch method in the
        reference too (``optim/LBFGS.scala``)."""
        model, criterion = self.model, self.criterion
        optim = self.optim_method
        from bigdl_tpu.utils import compile_cache

        def _value_and_grad(params, mstate, inputs, targets, rng):
            def loss_fn(p):
                out, _ = model.apply(p, inputs, mstate, training=True,
                                     rng=rng)
                loss = criterion.apply(out, targets)
                return loss + regularization_penalty(model, p)
            return jax.value_and_grad(loss_fn)(params)

        from bigdl_tpu.analysis import program_contracts
        value_and_grad = compile_cache.tracked_jit(
            _value_and_grad, label="local_feval",
            topology=self._topology_meta(),
            contract=program_contracts.feval_contract())

        def step(params, slots, mstate, inputs, targets, hyper, rng):
            def feval(p):
                return value_and_grad(p, mstate, inputs, targets, rng)
            new_params, losses = optim.optimize(feval, params)
            return new_params, slots, mstate, losses[-1]

        return step

    def _optimize(self) -> Module:
        model = self.model
        model.training()
        model._ensure_init()

        carry = {"params": model.params, "mstate": model.state,
                 "slots": self.optim_method.slots(model.params)}
        self.optim_method.state.setdefault("epoch", 1)
        if self._step_fn is None:
            self._step_fn = self._arm_retrace(self._build_step(), "local")

        from bigdl_tpu.utils import config as _config
        from bigdl_tpu import integrity as _integrity
        feval = getattr(self.optim_method, "requires_feval", False)
        guard = _config.get_bool("bigdl.divergence.guard", True)
        every_n = 0 if feval else _config.get_int(
            "bigdl.integrity.everyN", 0)
        integ = None
        if not feval and (guard or every_n > 0):
            integ = _integrity.DriverIntegrity(
                "local",
                _integrity.nonfinite_names(
                    ("loss", 0.0), ("grad", carry["params"])),
                every_n=every_n,
                health=_integrity.WeightHealthMonitor(
                    _config.get_float("bigdl.integrity.healthFactor", 0.0),
                    warmup=_config.get_int(
                        "bigdl.integrity.healthWarmup", 5),
                    cooldown=_config.get_int(
                        "bigdl.integrity.healthCooldown", 50)))
        if every_n > 0:
            carry["fpc"] = jnp.asarray(_integrity.init_carry())

        it = {"data": None}

        def reset_epoch():
            self.dataset.shuffle()
            it["data"] = self.dataset.data(train=True)

        def fetch_batch():
            batch = next(it["data"])
            # the OOM re-plan picks its chunk count k against the
            # observed global batch (k must divide it)
            self._plan_batch_size = batch.size()
            return (_to_device(batch.get_input()),
                    _to_device(batch.get_target()), batch.size())

        def run_step(inputs, targets, hyper, rng):
            flip = _chaos.take_bitflip() if _chaos.active() else None
            if flip is not None:
                # injected SDC: one mantissa bit of a live parameter
                # flips between steps — all_finite cannot see it; the
                # continuity fingerprint must
                carry["params"] = _integrity.bitflip_tree(
                    carry["params"], flip)
            args = [carry["params"], carry["slots"], carry["mstate"],
                    inputs, targets, hyper, rng]
            if every_n > 0:
                tick = self.optim_method.state.get("evalCounter", 0) + 1
                args += [carry["fpc"], np.int32(tick)]
            out = self._step_fn(*args)
            if len(out) == 5:
                (carry["params"], carry["slots"], carry["mstate"],
                 loss, aux) = out
                if "fpc" in aux:
                    carry["fpc"] = aux["fpc"]
                return loss, aux
            (carry["params"], carry["slots"], carry["mstate"],
             loss) = out
            return loss

        # telemetry MFU probe: the fused step's full argument tuple, for
        # the one-shot cost_analysis lowering (bigdl.telemetry.mfu)
        def _cost_args(inputs, targets, hyper, rng):
            args = (carry["params"], carry["slots"], carry["mstate"],
                    inputs, targets, hyper, rng)
            if every_n > 0:
                args += (carry["fpc"], np.int32(1))
            return args
        self._cost_args_fn = _cost_args

        def publish():
            self._publish(carry["params"], carry["slots"], carry["mstate"])

        self._sync_dataset_epoch()
        reset_epoch()
        self._drive(fetch_batch, run_step, reset_epoch, publish,
                    epoch_size=_epoch_records(self.dataset),
                    integrity=integ)
        return model


def _epoch_records(ds: AbstractDataSet) -> int:
    """Records per epoch, before batching transformers."""
    return ds.size()


