"""OptimMethod: gradient-descent rules as pure pytree updates.

Reference equivalent: ``optim/OptimMethod.scala`` + SGD/Adagrad/Adadelta/Adam/
Adamax/RMSprop/LBFGS — torch-optim ports mutating a flattened (weight, grad)
pair with a serializable state Table.

TPU-native design: every method is split into
- ``init_slots(params)`` — per-parameter slot pytrees (momentum, variance, …);
- ``pure_update(grads, params, slots, hyper) -> (new_params, new_slots)`` —
  a PURE array function.  ``hyper`` is a dict of *dynamic scalars* (lr, step
  count) computed host-side per iteration, passed as arguments so the jitted
  training step never retraces as the schedule decays the rate.  Branch-free
  (``jnp.where`` instead of first-step flags) so it traces cleanly and runs
  identically inside ``shard_map`` — which is how the ZeRO-1-style sharded
  update (reference ``optim/DistriOptimizer.scala:265-280``) is expressed.
- a stateful shell (``optimize(feval, x)``, ``update(grads, params)``)
  keeping the reference's API and state-dict conventions (``evalCounter``,
  ``epoch``, negated ``clr``).

Hyper-parameters follow the reference's names and defaults
(``optim/SGD.scala:38``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Any


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


class OptimMethod:
    """Base class.  ``state`` is a plain dict (the reference's state Table)."""

    def __init__(self):
        self.state: Dict[str, Any] = {"evalCounter": 0, "epoch": 1}
        self._slots = None

    # ---- pure core ------------------------------------------------------

    def init_slots(self, params: Params):
        return {}

    def hyper(self) -> Dict[str, float]:
        """Dynamic scalars for this step, computed host-side."""
        return {"t": float(self.state.get("evalCounter", 0))}

    def pure_update(self, grads: Params, params: Params, slots,
                    hyper: Dict[str, jnp.ndarray]) -> Tuple[Params, Any]:
        raise NotImplementedError(type(self).__name__)

    # ---- stateful shell -------------------------------------------------

    def slots(self, params: Params):
        if self._slots is None:
            self._slots = self.init_slots(params)
        return self._slots

    def set_slots(self, slots) -> None:
        self._slots = slots

    def step_done(self) -> None:
        """Advance host counters after a step."""
        self.state["evalCounter"] = self.state.get("evalCounter", 0) + 1

    def update(self, grads: Params, params: Params) -> Params:
        """Host-driven single update (non-jit convenience path)."""
        h = self.hyper()
        new_params, self._slots = self.pure_update(
            grads, params, self.slots(params), h)
        self.step_done()
        return new_params

    def optimize(self, feval: Callable[[Params], Tuple[jnp.ndarray, Params]],
                 params: Params) -> Tuple[Params, Tuple[jnp.ndarray, ...]]:
        """One step: ``feval`` returns (loss, grads) at ``params``
        (reference ``OptimMethod.optimize``)."""
        loss, grads = feval(params)
        return self.update(grads, params), (loss,)

    def get_hyper_parameter(self) -> str:
        clr = self.state.get("clr")
        return f"Current learning rate is {-clr}. " if clr is not None else ""

    def get_learning_rate(self) -> float:
        return -float(self.state.get("clr", 0.0))

    def clear_history(self) -> None:
        self.state = {"evalCounter": 0, "epoch": 1}
        self._slots = None

    def save(self, path: str, overwrite: bool = True) -> "OptimMethod":
        from bigdl_tpu.utils import file_io
        file_io.save(self, path, overwrite)
        return self

    @staticmethod
    def load(path: str) -> "OptimMethod":
        from bigdl_tpu.utils import file_io
        return file_io.load(path)

    def __getstate__(self):
        d = dict(self.__dict__)
        if d.get("_slots") is not None:
            d["_slots"] = jax.tree_util.tree_map(np.asarray, d["_slots"])
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        if getattr(self, "_slots", None) is not None:
            self._slots = jax.tree_util.tree_map(jnp.asarray, self._slots)


# ---------------------------------------------------------------------------
# learning-rate schedules (reference optim/SGD.scala:198-560)
# ---------------------------------------------------------------------------

class LearningRateSchedule:
    """Computes the current rate from the optimizer's host state and stores
    the negated value in ``state["clr"]`` (the reference's convention, so
    hyper-parameter log lines match)."""

    def update_hyper_parameter(self, optim: "SGD") -> None:
        raise NotImplementedError


class Default(LearningRateSchedule):
    """lr / (1 + nevals * lrd) (reference ``SGD.Default``)."""

    def update_hyper_parameter(self, optim: "SGD") -> None:
        n = optim.state["evalCounter"]
        optim.state["clr"] = -optim.learning_rate / (
            1 + n * optim.learning_rate_decay)


class Step(LearningRateSchedule):
    """lr * gamma^(floor(nevals / step_size)) (reference ``SGD.Step:316``)."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size = step_size
        self.gamma = gamma

    def update_hyper_parameter(self, optim: "SGD") -> None:
        n = optim.state["evalCounter"]
        optim.state["clr"] = -optim.learning_rate * (
            self.gamma ** (n // self.step_size))


class MultiStep(LearningRateSchedule):
    """(reference ``SGD.MultiStep:349``)."""

    def __init__(self, step_sizes, gamma: float):
        self.step_sizes = list(step_sizes)
        self.gamma = gamma

    def update_hyper_parameter(self, optim: "SGD") -> None:
        n = optim.state["evalCounter"]
        k = sum(1 for s in self.step_sizes if n >= s)
        optim.state["clr"] = -optim.learning_rate * (self.gamma ** k)


class EpochStep(LearningRateSchedule):
    """lr * gamma^(floor((epoch-1)/step)) (reference ``SGD.EpochStep:412``)."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size = step_size
        self.gamma = gamma

    def update_hyper_parameter(self, optim: "SGD") -> None:
        epoch = optim.state.get("epoch", 1)
        optim.state["clr"] = -optim.learning_rate * (
            self.gamma ** ((epoch - 1) // self.step_size))


class EpochDecay(LearningRateSchedule):
    """lr * 0.1^decayFn(epoch) (reference ``SGD.EpochDecay:385``)."""

    def __init__(self, decay_fn: Callable[[int], float]):
        self.decay_fn = decay_fn

    def update_hyper_parameter(self, optim: "SGD") -> None:
        epoch = optim.state.get("epoch", 1)
        optim.state["clr"] = -optim.learning_rate * (
            0.1 ** self.decay_fn(epoch))


class Poly(LearningRateSchedule):
    """lr * (1 - iter/max)^power (reference ``SGD.Poly:281``)."""

    def __init__(self, power: float, max_iteration: int):
        self.power = power
        self.max_iteration = max_iteration

    def update_hyper_parameter(self, optim: "SGD") -> None:
        n = optim.state["evalCounter"]
        if n > self.max_iteration:
            optim.state["clr"] = 0.0
        else:
            optim.state["clr"] = -optim.learning_rate * (
                (1.0 - n / self.max_iteration) ** self.power)


class Exponential(LearningRateSchedule):
    """lr * gamma^(iter/decayStep), optionally staircased
    (reference ``SGD.Exponential:467``)."""

    def __init__(self, decay_step: int, decay_rate: float,
                 stair_case: bool = False):
        self.decay_step = decay_step
        self.decay_rate = decay_rate
        self.stair_case = stair_case

    def update_hyper_parameter(self, optim: "SGD") -> None:
        n = optim.state["evalCounter"]
        p = n / self.decay_step
        if self.stair_case:
            p = float(int(p))
        optim.state["clr"] = -optim.learning_rate * (self.decay_rate ** p)


class NaturalExp(LearningRateSchedule):
    """lr * exp(-gamma * floor(iter/decayStep))
    (reference ``SGD.NaturalExp:446``)."""

    def __init__(self, decay_step: int, gamma: float):
        self.decay_step = decay_step
        self.gamma = gamma

    def update_hyper_parameter(self, optim: "SGD") -> None:
        n = optim.state["evalCounter"]
        optim.state["clr"] = -optim.learning_rate * float(
            np.exp(-self.gamma * (n // self.decay_step)))


class Regime:
    """(startEpoch, endEpoch, config) (reference ``SGD.Regime``)."""

    def __init__(self, start_epoch: int, end_epoch: int,
                 config: Dict[str, Any]):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.config = config


class EpochSchedule(LearningRateSchedule):
    """Per-epoch-range hyper-parameter regimes
    (reference ``SGD.EpochSchedule:224``)."""

    def __init__(self, regimes):
        self.regimes = list(regimes)

    def update_hyper_parameter(self, optim: "SGD") -> None:
        epoch = optim.state.get("epoch", 1)
        for r in self.regimes:
            if r.start_epoch <= epoch <= r.end_epoch:
                for k, v in r.config.items():
                    setattr(optim, k, v)
        optim.state["clr"] = -optim.learning_rate


class Plateau(LearningRateSchedule):
    """Reduce on metric plateau (reference ``SGD.Plateau:534``)."""

    def __init__(self, monitor: str = "score", factor: float = 0.1,
                 patience: int = 10, mode: str = "min", epsilon: float = 1e-4,
                 cooldown: int = 0, min_lr: float = 0.0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.mode = mode
        self.epsilon = epsilon
        self.cooldown = cooldown
        self.min_lr = min_lr
        self._wait = 0
        self._cooldown_counter = 0
        self._best: Optional[float] = None
        self._current_lr: Optional[float] = None
        self._cur_epoch = -1

    def _is_better(self, cur: float, best: float) -> bool:
        if self.mode == "min":
            return cur < best - self.epsilon
        return cur > best + self.epsilon

    def update_hyper_parameter(self, optim: "SGD") -> None:
        if self._current_lr is None:
            self._current_lr = optim.learning_rate
        optim.state["clr"] = -self._current_lr
        # advance the plateau state once per epoch, not per iteration
        # (reference ``SGD.Plateau:558`` — ``if (epoch == curEpoch) return``)
        epoch = optim.state.get("epoch", 1)
        if epoch == self._cur_epoch:
            return
        self._cur_epoch = epoch
        metric = optim.state.get(self.monitor)
        if metric is None:
            return
        if self._cooldown_counter > 0:
            self._cooldown_counter -= 1
            self._wait = 0
        if self._best is None or self._is_better(metric, self._best):
            self._best = metric
            self._wait = 0
        elif self._cooldown_counter <= 0:
            self._wait += 1
            if self._wait >= self.patience:
                self._current_lr = max(self._current_lr * self.factor,
                                       self.min_lr)
                self._cooldown_counter = self.cooldown
                self._wait = 0
        optim.state["clr"] = -self._current_lr


# ---------------------------------------------------------------------------
# concrete methods
# ---------------------------------------------------------------------------

class SGD(OptimMethod):
    """SGD with momentum/dampening/nesterov/weight-decay and pluggable LR
    schedules (reference ``optim/SGD.scala:38``)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0, momentum: float = 0.0,
                 dampening: Optional[float] = None, nesterov: bool = False,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None):
        super().__init__()
        if dampening is None:
            dampening = momentum if not nesterov else 0.0
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires momentum > 0 and dampening = 0 "
                "(reference SGD.scala requirement)")
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.weight_decay = weight_decay
        self.momentum = momentum
        self.dampening = dampening
        self.nesterov = nesterov
        self.schedule = learning_rate_schedule or Default()

    def init_slots(self, params):
        if self.momentum > 0:
            return {"dfdx": _tmap(jnp.zeros_like, params)}
        return {}

    def hyper(self):
        self.schedule.update_hyper_parameter(self)
        return {"lr": -self.state["clr"],
                "t": float(self.state.get("evalCounter", 0))}

    def pure_update(self, grads, params, slots, hyper):
        lr, t = hyper["lr"], hyper["t"]
        wd, mom, damp = self.weight_decay, self.momentum, self.dampening
        if wd != 0:
            grads = _tmap(lambda g, p: g + wd * p, grads, params)
        if mom > 0:
            # first step: v = g (torch convention); branch-free via where
            dfdx = _tmap(
                lambda v, g: jnp.where(t == 0, g, v * mom + (1 - damp) * g),
                slots["dfdx"], grads)
            slots = {"dfdx": dfdx}
            if self.nesterov:
                grads = _tmap(lambda g, v: g + mom * v, grads, dfdx)
            else:
                grads = dfdx
        new_params = _tmap(lambda p, g: p - lr * g, params, grads)
        return new_params, slots


class Adagrad(OptimMethod):
    """(reference ``optim/Adagrad.scala``)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0):
        super().__init__()
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.weight_decay = weight_decay

    def init_slots(self, params):
        return {"var": _tmap(jnp.zeros_like, params)}

    def hyper(self):
        n = self.state.get("evalCounter", 0)
        clr = self.learning_rate / (1 + n * self.learning_rate_decay)
        self.state["clr"] = -clr
        return {"lr": clr, "t": float(n)}

    def pure_update(self, grads, params, slots, hyper):
        lr = hyper["lr"]
        if self.weight_decay != 0:
            grads = _tmap(lambda g, p: g + self.weight_decay * p,
                          grads, params)
        var = _tmap(lambda v, g: v + g * g, slots["var"], grads)
        new_params = _tmap(
            lambda p, g, v: p - lr * g / (jnp.sqrt(v) + 1e-10),
            params, grads, var)
        return new_params, {"var": var}


class Adadelta(OptimMethod):
    """(reference ``optim/Adadelta.scala``; decayRate=0.9, epsilon=1e-10)."""

    def __init__(self, decay_rate: float = 0.9, epsilon: float = 1e-10):
        super().__init__()
        self.decay_rate = decay_rate
        self.epsilon = epsilon

    def init_slots(self, params):
        return {"paramVariance": _tmap(jnp.zeros_like, params),
                "delta": _tmap(jnp.zeros_like, params)}

    def pure_update(self, grads, params, slots, hyper):
        rho, eps = self.decay_rate, self.epsilon
        var = _tmap(lambda v, g: v * rho + (1 - rho) * g * g,
                    slots["paramVariance"], grads)
        upd = _tmap(
            lambda d, v, g: jnp.sqrt(d + eps) / jnp.sqrt(v + eps) * g,
            slots["delta"], var, grads)
        delta = _tmap(lambda d, u: d * rho + (1 - rho) * u * u,
                      slots["delta"], upd)
        new_params = _tmap(lambda p, u: p - u, params, upd)
        return new_params, {"paramVariance": var, "delta": delta}


class Adam(OptimMethod):
    """(reference ``optim/Adam.scala``; lr=1e-3, beta1=0.9, beta2=0.999,
    eps=1e-8, bias-corrected)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8):
        super().__init__()
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def init_slots(self, params):
        return {"s": _tmap(jnp.zeros_like, params),
                "r": _tmap(jnp.zeros_like, params)}

    def hyper(self):
        n = self.state.get("evalCounter", 0)
        clr = self.learning_rate / (1 + n * self.learning_rate_decay)
        self.state["clr"] = -clr
        return {"lr": clr, "t": float(n + 1)}

    def pure_update(self, grads, params, slots, hyper):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        lr, t = hyper["lr"], hyper["t"]
        s = _tmap(lambda m, g: b1 * m + (1 - b1) * g, slots["s"], grads)
        r = _tmap(lambda v, g: b2 * v + (1 - b2) * g * g, slots["r"], grads)
        bc1 = 1 - b1 ** t
        bc2 = 1 - b2 ** t
        new_params = _tmap(
            lambda p, m, v: p - lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps),
            params, s, r)
        return new_params, {"s": s, "r": r}


class Adamax(OptimMethod):
    """(reference ``optim/Adamax.scala``; lr=2e-3)."""

    def __init__(self, learning_rate: float = 2e-3, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-38):
        super().__init__()
        self.learning_rate = learning_rate
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def init_slots(self, params):
        return {"m": _tmap(jnp.zeros_like, params),
                "u": _tmap(jnp.zeros_like, params)}

    def hyper(self):
        n = self.state.get("evalCounter", 0)
        return {"lr": self.learning_rate, "t": float(n + 1)}

    def pure_update(self, grads, params, slots, hyper):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        lr, t = hyper["lr"], hyper["t"]
        m = _tmap(lambda m_, g: b1 * m_ + (1 - b1) * g, slots["m"], grads)
        u = _tmap(lambda u_, g: jnp.maximum(b2 * u_, jnp.abs(g) + eps),
                  slots["u"], grads)
        clr = lr / (1 - b1 ** t)
        new_params = _tmap(lambda p, m_, u_: p - clr * m_ / u_, params, m, u)
        return new_params, {"m": m, "u": u}


class RMSprop(OptimMethod):
    """(reference ``optim/RMSprop.scala``; lr=1e-2, decayRate=0.99)."""

    def __init__(self, learning_rate: float = 1e-2,
                 learning_rate_decay: float = 0.0,
                 decay_rate: float = 0.99, epsilon: float = 1e-8):
        super().__init__()
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.decay_rate = decay_rate
        self.epsilon = epsilon

    def init_slots(self, params):
        return {"sumSquare": _tmap(jnp.zeros_like, params)}

    def hyper(self):
        n = self.state.get("evalCounter", 0)
        clr = self.learning_rate / (1 + n * self.learning_rate_decay)
        self.state["clr"] = -clr
        return {"lr": clr, "t": float(n)}

    def pure_update(self, grads, params, slots, hyper):
        rho, eps = self.decay_rate, self.epsilon
        lr = hyper["lr"]
        r = _tmap(lambda v, g: rho * v + (1 - rho) * g * g,
                  slots["sumSquare"], grads)
        new_params = _tmap(
            lambda p, g, v: p - lr * g / (jnp.sqrt(v) + eps),
            params, grads, r)
        return new_params, {"sumSquare": r}


class LBFGS(OptimMethod):
    """Limited-memory BFGS with optional strong-Wolfe line search
    (reference ``optim/LBFGS.scala``; inherently sequential — host-driven,
    operating on the flattened parameter vector like the reference)."""

    # the trainer uses the host-driven optimize(feval, x) path instead of
    # the fused pure_update step (line search re-evaluates the loss)
    requires_feval = True

    def __init__(self, max_iter: int = 20, max_eval: Optional[float] = None,
                 tolerance_fun: float = 1e-5, tolerance_x: float = 1e-9,
                 n_correction: int = 100, learning_rate: float = 1.0,
                 line_search: bool = False):
        super().__init__()
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 1.25
        self.tolerance_fun = tolerance_fun
        self.tolerance_x = tolerance_x
        self.n_correction = n_correction
        self.learning_rate = learning_rate
        self.line_search = line_search

    def pure_update(self, grads, params, slots, hyper):
        raise NotImplementedError(
            "LBFGS needs re-evaluation inside the step; use optimize(feval, x)")

    def optimize(self, feval, x):
        """Multi-evaluation inner loop per optimize() call (torch lbfgs
        semantics).  ``x`` may be any pytree; flattened internally."""
        leaves, treedef = jax.tree_util.tree_flatten(x)
        shapes = [l.shape for l in leaves]

        def to_flat(t):
            ls = jax.tree_util.tree_leaves(t)
            return jnp.concatenate([jnp.ravel(l) for l in ls])

        def from_flat(vec):
            out, off = [], 0
            for shp in shapes:
                n = int(np.prod(shp)) if shp else 1
                out.append(jnp.reshape(vec[off:off + n], shp))
                off += n
            return jax.tree_util.tree_unflatten(treedef, out)

        def feval_flat(vec):
            loss, g = feval(from_flat(vec))
            return float(loss), to_flat(g)

        f, g = feval_flat(to_flat(x))
        xv = to_flat(x)
        losses = [f]
        n_eval = 1

        old_dirs = self.state.setdefault("old_dirs", [])
        old_stps = self.state.setdefault("old_stps", [])
        hdiag = self.state.get("Hdiag", 1.0)
        prev_g = self.state.get("prev_g")
        prev_loss = self.state.get("prev_loss", f)

        for _ in range(self.max_iter):
            if float(jnp.abs(g).max()) <= 1e-10:
                break
            if prev_g is not None and "prev_step" in self.state:
                y = g - prev_g
                s = self.state["prev_step"]
                ys = float(y @ s)
                if ys > 1e-10:
                    if len(old_dirs) == self.n_correction:
                        old_dirs.pop(0)
                        old_stps.pop(0)
                    old_dirs.append(s)
                    old_stps.append(y)
                    hdiag = ys / float(y @ y)
            # L-BFGS two-loop recursion
            k = len(old_dirs)
            ro = [1.0 / float(old_stps[i] @ old_dirs[i]) for i in range(k)]
            al = [0.0] * k
            q = -g
            for i in range(k - 1, -1, -1):
                al[i] = float(old_dirs[i] @ q) * ro[i]
                q = q - al[i] * old_stps[i]
            d = q * hdiag
            for i in range(k):
                be = float(old_stps[i] @ d) * ro[i]
                d = d + old_dirs[i] * (al[i] - be)

            gtd = float(g @ d)
            if gtd > -self.tolerance_x:
                break
            if prev_g is None:
                t = min(1.0, 1.0 / float(jnp.abs(g).sum())) * self.learning_rate
            else:
                t = self.learning_rate

            prev_g = g
            self.state["prev_g"] = g
            if self.line_search:
                t, f, g, xv, ls_evals = _lswolfe(feval_flat, xv, t, d, f, g,
                                                 gtd)
                n_eval += ls_evals
            else:
                xv = xv + t * d
                f, g = feval_flat(xv)
                n_eval += 1
            losses.append(f)
            self.state["prev_step"] = t * d

            if n_eval >= self.max_eval:
                break
            if abs(losses[-1] - prev_loss) < self.tolerance_fun:
                break
            prev_loss = losses[-1]
            self.state["prev_loss"] = prev_loss

        self.state["Hdiag"] = hdiag
        self.state["evalCounter"] = self.state.get("evalCounter", 0) + 1
        return from_flat(xv), tuple(losses)


def _lswolfe(feval_flat, xv, t, d, f, g, gtd,
             c1: float = 1e-4, c2: float = 0.9, max_ls: int = 25):
    """Backtracking/extending strong-Wolfe line search (torch lswolfe analog,
    simplified bracketing).  Returns (t, f, g, x) all evaluated at the SAME
    point ``xv + t*d`` so the caller's curvature pair stays consistent."""
    f0, gtd0 = f, gtd
    evals = 0
    f_prev = f
    # best-so-far evaluated point (step, loss, gradient)
    t_eval, f_eval, g_eval = 0.0, f, g
    for _ in range(max_ls):
        f_new, g_new = feval_flat(xv + t * d)
        evals += 1
        t_eval, f_eval, g_eval = t, f_new, g_new
        gtd_new = float(g_new @ d)
        if f_new > f0 + c1 * t * gtd0 or (evals > 1 and f_new >= f_prev):
            t = t * 0.5
            continue
        if abs(gtd_new) <= -c2 * gtd0:
            break
        if gtd_new >= 0:
            t = t * 0.5
            continue
        f_prev = f_new
        t = t * 2.0
    return t_eval, f_eval, g_eval, xv + t_eval * d, evals
