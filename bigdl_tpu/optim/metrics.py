"""Metrics: named driver-side counters for phase timing.

Reference equivalent: ``optim/Metrics.scala:31`` — named counters backed by
Spark accumulators (local / aggregated-distributed / per-node list).  Here a
process-local dict with the same set/add/get surface; the distributed trainer
aggregates per-shard values before recording.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple, Union


class Metrics:
    def __init__(self):
        self._scalar: Dict[str, Tuple[float, int]] = {}   # value, parallelism
        self._lists: Dict[str, List[float]] = {}
        self._lock = threading.Lock()

    def set(self, name: str, value: Union[float, List[float]],
            parallelism: int = 1) -> None:
        with self._lock:
            if isinstance(value, (list, tuple)):
                self._lists[name] = list(value)
            else:
                self._scalar[name] = (float(value), parallelism)

    def add(self, name: str, value: float) -> None:
        with self._lock:
            if name in self._lists:
                self._lists[name].append(float(value))
            elif name in self._scalar:
                v, p = self._scalar[name]
                self._scalar[name] = (v + float(value), p)
            else:
                self._scalar[name] = (float(value), 1)

    def get(self, name: str):
        with self._lock:
            if name in self._scalar:
                v, p = self._scalar[name]
                return v / p
            if name in self._lists:
                return list(self._lists[name])
            raise KeyError(name)

    def aggregated(self, name: str) -> float:
        """Cross-process aggregate of a scalar counter — the reference's
        *distributed* accumulator kind (``optim/Metrics.scala:31``: Spark
        accumulators summed over executors).  Sums (value, parallelism)
        over every process and returns the global mean; single-process
        this equals :meth:`get`.  COLLECTIVE under multi-host: every
        process must call it with the same name."""
        from bigdl_tpu.engine import allgather_sum

        with self._lock:
            v, p = self._scalar.get(name, (0.0, 0))
        total_v, total_p = allgather_sum([v, float(p)])
        if total_p == 0:
            raise KeyError(name)
        return float(total_v / total_p)

    def summary(self, unit: str = "s", scale: float = 1e9) -> str:
        with self._lock:
            parts = [f"{k}: {v / p / scale} {unit}"
                     for k, (v, p) in self._scalar.items()]
        return "========== Metrics Summary ==========\n" + \
            "\n".join(parts) + "\n====================================="
