"""Metrics: named driver-side counters for phase timing.

Reference equivalent: ``optim/Metrics.scala:31`` — named counters backed by
Spark accumulators (local / aggregated-distributed / per-node list).  Here a
process-local dict with the same set/add/get surface; the distributed trainer
aggregates per-shard values before recording.

Hot-path contract: :meth:`add` accepts DEVICE scalars without coercion —
a ``float(device_value)`` per call would be one blocking device round-trip
per iteration, exactly the implicit host sync the analysis pass forbids.
Device values are parked as-is and pulled in ONE explicit ``device_get``
when a reader (:meth:`get` / :meth:`aggregated` / :meth:`summary`) actually
needs host numbers.
"""

from __future__ import annotations

from typing import Dict, List, Tuple, Union

from bigdl_tpu import analysis


def _is_device_value(v) -> bool:
    """True for jax device arrays (anything carrying an abstract value);
    plain python/numpy scalars convert for free and are folded eagerly."""
    return hasattr(v, "aval")


class Metrics:
    def __init__(self):
        self._scalar: Dict[str, Tuple[float, int]] = {}   # value, parallelism
        self._lists: Dict[str, List[float]] = {}
        self._pending: Dict[str, list] = {}   # device scalars, not yet pulled
        self._lock = analysis.make_lock("metrics.optim")
        # serializes flushes and resets: the blocking device pull happens
        # outside _lock (a reader must not stall hot-loop adds for a device
        # round-trip), so without this a set() could slip between a flush's
        # swap-out and fold-in and have pre-reset values folded on top of
        # it, and a second reader could observe the transient gap
        self._flush_lock = analysis.make_lock("metrics.flush")

    def set(self, name: str, value: Union[float, List[float]],
            parallelism: int = 1) -> None:
        with self._flush_lock, self._lock:
            if isinstance(value, (list, tuple)):
                self._lists[name] = list(value)
            else:
                self._pending.pop(name, None)
                self._scalar[name] = (float(value), parallelism)

    #: parked device scalars per name before they are compacted into one
    #: on-device sum (an async dispatch, never a sync) — bounds live
    #: buffers on long runs that are only read at the end
    COMPACT_AT = 256

    def add(self, name: str, value: float) -> None:
        if _is_device_value(value):
            # accumulate on device: park the scalar un-synced; one batched
            # pull happens at read time (get/aggregated/summary)
            with self._lock:
                lst = self._pending.setdefault(name, [])
                lst.append(value)
                if len(lst) >= self.COMPACT_AT:
                    import jax.numpy as jnp
                    self._pending[name] = [jnp.sum(jnp.stack(lst))]
            return
        with self._lock:
            self._add_host(name, float(value))

    def _add_host(self, name: str, value: float) -> None:
        """Fold one host float in (caller holds the lock)."""
        if name in self._lists:
            self._lists[name].append(value)
        elif name in self._scalar:
            v, p = self._scalar[name]
            self._scalar[name] = (v + value, p)
        else:
            self._scalar[name] = (value, 1)

    def _flush_pending(self) -> None:
        """Pull every parked device scalar in one explicit device_get and
        fold the host values in.  The blocking pull happens OUTSIDE
        ``_lock`` (a reader must not stall a concurrent hot-loop ``add``
        for a device round-trip); ``_flush_lock`` keeps the whole
        swap-out → pull → fold-in atomic w.r.t. other readers and
        ``set`` resets."""
        with self._flush_lock:
            with self._lock:
                if not self._pending:
                    return
                pending, self._pending = self._pending, {}
            from bigdl_tpu.analysis.hostsync import host_pull
            pulled = host_pull(pending, what="metrics flush")
            with self._lock:
                for name, values in pulled.items():
                    for v in values:
                        self._add_host(name, float(v))

    def get(self, name: str):
        self._flush_pending()
        with self._lock:
            if name in self._scalar:
                v, p = self._scalar[name]
                return v / p
            if name in self._lists:
                return list(self._lists[name])
            raise KeyError(name)

    def aggregated(self, name: str) -> float:
        """Cross-process aggregate of a scalar counter — the reference's
        *distributed* accumulator kind (``optim/Metrics.scala:31``: Spark
        accumulators summed over executors).  Sums (value, parallelism)
        over every process and returns the global mean; single-process
        this equals :meth:`get`.  COLLECTIVE under multi-host: every
        process must call it with the same name."""
        from bigdl_tpu.engine import allgather_sum

        self._flush_pending()
        with self._lock:
            v, p = self._scalar.get(name, (0.0, 0))
        total_v, total_p = allgather_sum([v, float(p)])
        if total_p == 0:
            raise KeyError(name)
        return float(total_v / total_p)

    def summary(self, unit: str = "s", scale: float = 1e9) -> str:
        self._flush_pending()
        with self._lock:
            parts = [f"{k}: {v / p / scale} {unit}"
                     for k, (v, p) in self._scalar.items()]
        return "========== Metrics Summary ==========\n" + \
            "\n".join(parts) + "\n====================================="
