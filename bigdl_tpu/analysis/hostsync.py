"""Host-sync guard: catch implicit device→host pulls in the hot loop.

An implicit device→host sync — a stray ``float(loss)``, ``np.asarray(out)``,
``if x:`` on a device value — blocks the dispatch pipeline for a full device
round-trip (a network RTT on a tunneled chip) and serializes the driver loop
against device compute.  One of them inside the per-iteration hot loop undoes
the entire dispatch-pipelining design.

Two detection tiers, both scoped to the ARMED region on the ARMING thread:

- **JAX transfer guards** (``jax.transfer_guard_device_to_host``): on real
  accelerators every implicit device→host copy errors (strict) or logs
  (warn).  On the CPU backend arrays are host-resident so this tier never
  fires — which is why tier two exists.
- **Instrumented conversion hooks**: the array type's ``__float__`` /
  ``__int__`` / ``__bool__`` / ``__index__`` / ``item`` / ``tolist`` /
  ``__array__`` are wrapped once, process-wide; inside an armed region they
  report the offending call-site (file:line of the first frame outside jax
  and this module) before delegating.  Backend-independent, so the tier-1
  CPU test suite exercises the same contract production TPU runs enforce.

Intended pulls go through :func:`host_pull` — the explicit ``device_get``
choke point (validation outputs, the per-iteration loss read) — or an
:func:`allow_host_sync` region.  Both are counted, so a run can report
exactly how many host round-trips its hot loop performed.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import traceback
from typing import Any, Optional

logger = logging.getLogger("bigdl_tpu")

_TLS = threading.local()


def _tls():
    if not hasattr(_TLS, "armed"):
        _TLS.armed = 0
        _TLS.allow = 0
        _TLS.mode = "warn"
    return _TLS


class HostSyncError(ValueError):
    """An implicit device→host sync happened inside an armed hot-loop
    region.  Subclasses ``ValueError``: this is a programming error the
    failure-retry loop must surface, not retry around."""


class _Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.implicit = 0          # implicit syncs observed while armed
        self.explicit_pulls = 0    # host_pull calls
        self.warned_sites = set()

    def snapshot(self) -> dict:
        with self.lock:
            return {"implicit": self.implicit,
                    "explicit_pulls": self.explicit_pulls}


STATS = _Stats()

_HOOK_NAMES = ("__float__", "__int__", "__index__", "__complex__",
               "__bool__", "item", "tolist", "__array__")
_installed = {"done": False}
_INSTALL_LOCK = threading.Lock()


def _call_site() -> str:
    """file:line of the frame that triggered the conversion — the first
    frame below this module that is user/package code (jaxlib/numpy/jax
    internals are skipped so the diagnostic names the actual pull site)."""
    for frame in reversed(traceback.extract_stack()):
        f = frame.filename
        if (f.endswith("hostsync.py") or "jax/_src" in f or
                "jaxlib" in f or "numpy" in f):
            continue
        return f"{frame.filename}:{frame.lineno} in {frame.name}"
    return "<unknown call site>"


def _report(op: str, arr) -> None:
    st = _tls()
    site = _call_site()
    shape = getattr(arr, "shape", "?")
    dtype = getattr(arr, "dtype", "?")
    msg = (f"implicit device→host sync via {op} on a device value "
           f"(shape={shape}, dtype={dtype}) inside the sanitized hot loop "
           f"at {site} — route intended pulls through "
           "bigdl_tpu.analysis.host_pull(...) (explicit device_get) or an "
           "allow_host_sync() region; silence the pass with "
           "bigdl.analysis.hostSync=off")
    with STATS.lock:
        STATS.implicit += 1
        fresh = site not in STATS.warned_sites
        STATS.warned_sites.add(site)
    if st.mode == "strict":
        raise HostSyncError(msg)
    if fresh:                      # warn once per call-site, count always
        logger.warning("%s", msg)


def _make_hook(name: str, orig):
    def hook(self, *args, **kwargs):
        st = _tls()
        if st.armed > 0 and st.allow == 0:
            _report(name, self)
        return orig(self, *args, **kwargs)
    hook.__name__ = name
    hook._bigdl_hostsync_orig = orig
    return hook


def _install_hooks() -> bool:
    """Wrap the conversion dunders on the concrete jax array type, once per
    process.  The wrappers delegate untouched unless the calling thread is
    inside an armed region, so cost outside the guard is one extra Python
    call on conversions only."""
    with _INSTALL_LOCK:
        if _installed["done"]:
            return True
        try:
            import jax.numpy as jnp
            arr_t = type(jnp.zeros(()))
            for name in _HOOK_NAMES:
                orig = getattr(arr_t, name, None)
                if orig is None or hasattr(orig, "_bigdl_hostsync_orig"):
                    continue
                setattr(arr_t, name, _make_hook(name, orig))
            _installed["done"] = True
            return True
        except Exception as e:  # pragma: no cover - exotic jax builds
            logger.warning("host-sync hooks unavailable on this jax "
                           "build (%s); transfer guards only", e)
            _installed["done"] = True
            return False


@contextlib.contextmanager
def allow_host_sync():
    """Explicitly permit device→host syncs inside an armed region (the
    validation/metrics escape hatch for code that cannot batch through
    :func:`host_pull`)."""
    st = _tls()
    st.allow += 1
    try:
        yield
    finally:
        st.allow -= 1


def host_pull(x: Any, what: str = "") -> Any:
    """The explicit device→host choke point: one ``jax.device_get`` for the
    whole (possibly nested) value, permitted inside armed regions and
    counted.  Use it wherever the hot loop or a validation step genuinely
    needs host values — one batched pull instead of N implicit ones."""
    import jax
    st = _tls()
    st.allow += 1
    try:
        try:
            ctx = jax.transfer_guard_device_to_host("allow")
        except Exception:  # pragma: no cover - very old jax
            ctx = contextlib.nullcontext()
        with ctx:
            out = jax.device_get(x)
    finally:
        st.allow -= 1
    with STATS.lock:
        STATS.explicit_pulls += 1
    return out


class HostSyncGuard:
    """Arms the host-sync pass around a hot-loop region.

    ``with guard.armed(): ...`` — inside, implicit device→host conversions
    on THIS thread raise (strict) or log-once-per-site and count (warn).
    Produced by :meth:`from_config` (``bigdl.analysis.hostSync``); a None
    guard from a disabled config is replaced by :data:`NULL_GUARD`, whose
    ``armed()`` is free."""

    def __init__(self, mode: str = "warn"):
        self.mode = mode
        self.enabled = mode in ("strict", "warn")
        if self.enabled:
            _install_hooks()

    @classmethod
    def from_config(cls) -> "HostSyncGuard":
        from bigdl_tpu.analysis import pass_mode
        mode = pass_mode("hostSync")
        if mode == "off":
            return NULL_GUARD
        return cls(mode)

    @contextlib.contextmanager
    def armed(self):
        if not self.enabled:
            yield
            return
        import jax
        st = _tls()
        prev_mode = st.mode
        st.mode = self.mode
        st.armed += 1
        try:
            # tier one: real accelerators fail implicit D2H copies in the
            # runtime itself ("disallow"); warn mode logs them.  Explicit
            # device_get stays allowed in both — that is the choke point.
            guard_level = "disallow" if self.mode == "strict" else "log"
            try:
                ctx = jax.transfer_guard_device_to_host(guard_level)
            except Exception:  # pragma: no cover - very old jax
                ctx = contextlib.nullcontext()
            try:
                with ctx:
                    yield
            except RuntimeError as e:
                # the runtime-level guard (tier one, real accelerators)
                # raises jax's own RuntimeError for pulls the conversion
                # hooks don't cover; translate it so the failure-retry
                # loop treats it as the programming error it is instead
                # of restoring a snapshot and retrying
                msg = str(e)
                if "transfer" in msg.lower() and "guard" in msg.lower():
                    raise HostSyncError(
                        f"implicit device→host transfer inside the "
                        f"sanitized hot loop (jax transfer guard): {msg} — "
                        "route intended pulls through "
                        "bigdl_tpu.analysis.host_pull(...)") from e
                raise
        finally:
            st.armed -= 1
            st.mode = prev_mode

    @property
    def implicit_syncs(self) -> int:
        return STATS.snapshot()["implicit"]


class _NullGuard(HostSyncGuard):
    def __init__(self):
        self.mode = "off"
        self.enabled = False


NULL_GUARD = _NullGuard()
