"""Module contract checker: shape/dtype/layout static analysis, zero FLOPs.

Every :class:`~bigdl_tpu.nn.module.Module` may declare a
:class:`ModuleContract` (class attribute ``contract`` or per-instance
``declare_contract``): the input rank(s) it accepts, its dtype policy, and
whether its float output is expected to follow its float input dtype.
:func:`check_model` then walks a model ONCE under ``jax.eval_shape`` — the
forward runs on abstract values, so a ResNet-50 checks in milliseconds with
no device work — and reports:

- **contract violations**: an input rank or dtype a module declared it
  cannot take (the errors that otherwise surface as cryptic XLA shape
  failures two hours into a run);
- **promotion drift**: a float output wider than the module's float input
  (bf16 in → f32 out silently runs the rest of the network at double cost)
  and any float64/complex128 leaf (x64 drift);
- **layout violations**: a spatial module configured ``NCHW`` executing
  inside an NHWC region (or vice versa) — closing the loop on the
  channels-last conversion in ``nn/layout.py``.

Interception instruments each module instance's ``apply`` for the duration
of one traced forward, so recorded shapes/dtypes are exactly what the jitted
training step would see.  ``bigdl.analysis.contracts`` picks strict
(:class:`ContractError`) / warn / off behaviour for :meth:`ContractReport.
raise_if_strict`.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple

logger = logging.getLogger("bigdl_tpu")


class ContractError(ValueError):
    """A model violated a declared module contract (strict mode)."""


@dataclass(frozen=True)
class ModuleContract:
    """Declarative IO contract for one module class/instance.

    ``input_ndim``: allowed rank(s) of array inputs (None = any).
    ``dtypes``: "float", "int", "any", or an explicit dtype-name tuple.
    ``follows_input_dtype``: when True (default for float-to-float compute
    modules), a float output wider than the widest float input is reported
    as promotion drift."""

    input_ndim: Optional[Tuple[int, ...]] = None
    dtypes: Any = "any"
    follows_input_dtype: bool = True

    def __post_init__(self):
        nd = self.input_ndim
        if isinstance(nd, int):
            object.__setattr__(self, "input_ndim", (nd,))
        elif nd is not None:
            object.__setattr__(self, "input_ndim", tuple(nd))

    def allows_dtype(self, dtype) -> bool:
        # jnp.issubdtype, not np: ml_dtypes' bfloat16 is floating to jax
        # but alien to numpy's lattice
        import jax.numpy as jnp
        if self.dtypes == "any":
            return True
        if self.dtypes == "float":
            return jnp.issubdtype(dtype, jnp.floating)
        if self.dtypes == "int":
            return jnp.issubdtype(dtype, jnp.integer)
        return str(dtype) in tuple(self.dtypes)


@dataclass
class Violation:
    module: str            # container path, e.g. Sequential[3].SpatialConvolution
    kind: str              # "ndim" | "dtype" | "promotion" | "x64" | "layout"
    detail: str

    def __str__(self):
        return f"[{self.kind}] {self.module}: {self.detail}"


def _module_paths(model) -> dict:
    """id(module) -> container path (``Sequential[3].SpatialConvolution``,
    nested containers chain: ``Sequential[0].Sequential[2].Linear``) for
    every module reachable through container children.  A bare class
    name locates nothing in a zoo-sized model; the indexed path does."""
    from bigdl_tpu.nn.module import Container
    paths: dict = {}

    def walk(m, prefix: str) -> None:
        if isinstance(m, Container):
            for i, child in enumerate(m.children):
                cp = f"{prefix}[{i}].{type(child).__name__}"
                paths[id(child)] = cp
                walk(child, cp)

    walk(model, type(model).__name__)
    return paths


@dataclass
class ContractReport:
    violations: List[Violation] = field(default_factory=list)
    modules_checked: int = 0
    trace_error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return not self.violations and self.trace_error is None

    def by_kind(self, kind: str) -> List[Violation]:
        return [v for v in self.violations if v.kind == kind]

    def __str__(self):
        if self.ok:
            return (f"contract check: {self.modules_checked} modules, "
                    "no violations")
        lines = [f"contract check: {self.modules_checked} modules, "
                 f"{len(self.violations)} violation(s)"]
        lines += [f"  {v}" for v in self.violations]
        if self.trace_error:
            lines.append(f"  trace aborted: {self.trace_error}")
        return "\n".join(lines)

    def raise_if_strict(self, mode: Optional[str] = None) -> "ContractReport":
        from bigdl_tpu.analysis import pass_mode
        mode = mode or pass_mode("contracts")
        if self.ok or mode == "off":
            return self
        if mode == "strict":
            raise ContractError(str(self))
        logger.warning("%s", self)
        return self


def _array_leaves(x) -> List:
    import jax
    return [l for l in jax.tree_util.tree_leaves(x)
            if hasattr(l, "shape") and hasattr(l, "dtype")]


def _widest_float(leaves):
    import jax.numpy as jnp
    import numpy as np
    widths = [np.dtype(l.dtype).itemsize for l in leaves
              if jnp.issubdtype(l.dtype, jnp.floating)]
    return max(widths) if widths else None


def check_model(model, sample_input, *, training: bool = False,
                rng=None, mode: Optional[str] = None) -> ContractReport:
    """Walk ``model`` over ``sample_input`` with ``jax.eval_shape`` and
    check every module's declared contract plus the global dtype/layout
    invariants.  ``sample_input`` may be concrete arrays or
    ``jax.ShapeDtypeStruct`` trees — either way no FLOPs run.

    Violations are collected even when the trace itself dies (a rank
    mismatch usually kills the trace a layer later with an opaque XLA
    error; the report then carries both the contract finding and the trace
    error)."""
    import jax
    import numpy as np
    from bigdl_tpu.nn.module import Container
    from bigdl_tpu.nn.layout import NCHWToNHWC, NHWCToNCHW

    model._ensure_init()
    paths = _module_paths(model)
    report = ContractReport()
    region = {"layout": "NCHW"}    # facade layout at the model boundary
    instrumented: List[Any] = []

    def _check_inputs(m, inputs) -> None:
        """Input-side checks run BEFORE the module's apply, so a violation
        is on record even when the mismatch kills the trace a moment
        later with an opaque shape error."""
        report.modules_checked += 1
        where = paths.get(id(m), m.name)
        in_leaves = _array_leaves(inputs)
        contract: Optional[ModuleContract] = getattr(m, "contract", None)
        if contract is not None:
            for l in in_leaves:
                if (contract.input_ndim is not None and
                        len(l.shape) not in contract.input_ndim):
                    report.violations.append(Violation(
                        where, "ndim",
                        f"input rank {len(l.shape)} (shape {tuple(l.shape)}) "
                        f"not in declared {contract.input_ndim}"))
                if not contract.allows_dtype(np.dtype(l.dtype)):
                    report.violations.append(Violation(
                        where, "dtype",
                        f"input dtype {l.dtype} violates declared policy "
                        f"{contract.dtypes!r}"))
        # layout: a spatial op must match the region the boundary
        # transposes established
        if getattr(m, "layout_role", "opaque") == "spatial":
            fmt = getattr(m, "format", "NCHW")
            if any(len(l.shape) in (3, 4) for l in in_leaves) and \
                    fmt != region["layout"]:
                report.violations.append(Violation(
                    where, "layout",
                    f"{fmt}-configured spatial op inside an "
                    f"{region['layout']} region — the boundary transposes "
                    "and the op's data format disagree"))

    def _check_outputs(m, inputs, outputs) -> None:
        where = paths.get(id(m), m.name)
        in_leaves = _array_leaves(inputs)
        out_leaves = _array_leaves(outputs)
        contract: Optional[ModuleContract] = getattr(m, "contract", None)
        # x64 drift: any leaf at double width is almost always accidental
        # promotion (jax_enable_x64 plus a weak-typed python scalar)
        for l in out_leaves:
            if str(l.dtype) in ("float64", "complex128"):
                report.violations.append(Violation(
                    where, "x64",
                    f"output leaf is {l.dtype} — x64 promotion drift"))
        # precision promotion: float out wider than float in
        if contract is None or contract.follows_input_dtype:
            win, wout = _widest_float(in_leaves), _widest_float(out_leaves)
            if win is not None and wout is not None and wout > win:
                report.violations.append(Violation(
                    where, "promotion",
                    f"float output widens {win * 8}-bit input to "
                    f"{wout * 8}-bit — promotion drift (a constant or "
                    "state leaf is pinning a wider dtype)"))

    def _instrument(m) -> None:
        inner = m.apply

        if isinstance(m, NCHWToNHWC):
            def wrapped(params, input, state, **kw):
                out = inner(params, input, state, **kw)
                region["layout"] = "NHWC"
                return out
        elif isinstance(m, NHWCToNCHW):
            def wrapped(params, input, state, **kw):
                out = inner(params, input, state, **kw)
                region["layout"] = "NCHW"
                return out
        elif isinstance(m, Container):
            wrapped = None          # containers only orchestrate children
        else:
            def wrapped(params, input, state, **kw):
                _check_inputs(m, input)
                out = inner(params, input, state, **kw)
                _check_outputs(m, input,
                               out[0] if isinstance(out, tuple) else out)
                return out
        if wrapped is not None:
            # instance attribute shadows the class method for the walk only
            m.apply = wrapped
            instrumented.append(m)

    for m in model.modules():
        _instrument(m)
    model.clear_jit_cache()
    try:
        def fwd(params, x, state, key):
            out, _ = model.apply(params, x, state, training=training,
                                 rng=key)
            return out

        if rng is None:
            rng = jax.random.PRNGKey(0)
        try:
            jax.eval_shape(fwd, model.params, sample_input, model.state, rng)
        except (ContractError,):
            raise
        except Exception as e:  # the trace died — report what we saw first
            report.trace_error = f"{type(e).__name__}: {e}"
    finally:
        for m in instrumented:
            m.__dict__.pop("apply", None)
        model.clear_jit_cache()
    return report.raise_if_strict(mode)
