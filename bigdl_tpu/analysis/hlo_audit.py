"""HLO program auditor: static passes over every fused step's lowered IR.

PR 8's compile path already lowers every fused step to StableHLO (the
cache key is its digest) — this module finally LOOKS at that text.
Three pass families run at compile (or cache warm-load) time, hooked
into ``compile_cache.CachedStep``, and offline over a persisted cache
directory (``python -m bigdl_tpu.analysis.hlo_audit <cacheDir>``):

1. **collective contracts** (``bigdl.audit.collectives``) — every
   all-reduce / all-gather / reduce-scatter / all-to-all /
   collective-permute is extracted with its operand/result byte counts
   and replica groups, aggregated into a per-step communication budget
   (``Audit/collective_bytes`` + per-kind op counters in the telemetry
   registry), and checked against the :class:`~bigdl_tpu.analysis.
   program_contracts.StepContract` the owning trainer declared.  An
   undeclared kind, an op-count over ``max_ops`` or under ``min_ops``
   (the bucketed ZeRO-1 schedule promises a collective PER BUCKET — a
   missing one is a silently-unreduced parameter range), or aggregate
   traffic over ``max_bytes`` is a structured
   :class:`~bigdl_tpu.analysis.program_contracts.
   ProgramContractViolation` naming the HLO op, its shapes, and the
   owning step.
2. **precision drift** (``bigdl.audit.precision``) — any f64 op
   anywhere (x64 drift at the level that actually executes), and any
   f32-operand ``dot_general``/``convolution`` inside a program whose
   declared activation dtype is bf16 (an upcast the module-level
   checker can miss once jit fuses it).
3. **memory/layout budgets** (``bigdl.audit.memory``) — peak-buffer
   estimate from ``compiled.memory_analysis()`` plus a transpose
   census (generalizing PR 1's one-off ResNet HLO assertion): rank-4
   transposes beyond the contract's ``max_rank4_transposes`` are a
   violation; the census and peak bytes are always exported so the
   bench trajectory (``bench.py --audit-only`` → ``bench_audit.json``
   vs the committed ``audit_baselines.json``) catches regressions
   rather than absolutes.

Modes mirror ``bigdl.analysis.*``: ``strict`` raises
:class:`ProgramContractError` at compile time, ``warn`` logs the
structured report, ``off`` disables the pass (tier-1 arms all three
strict via the conftest autouse fixture).
"""

from __future__ import annotations

import json
import logging
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from bigdl_tpu.analysis.program_contracts import (COLLECTIVE_KINDS,
                                                  ProgramContractError,
                                                  ProgramContractViolation,
                                                  StepContract)

logger = logging.getLogger("bigdl_tpu")

_MODES = ("strict", "warn", "off")
_PASSES = ("collectives", "precision", "memory")


def audit_mode(key: str, default: str = "warn") -> str:
    """Resolve an audit pass's mode from ``bigdl.audit.<key>`` —
    identical semantics to ``analysis.pass_mode`` (unknown values
    degrade to ``off``, loudly)."""
    from bigdl_tpu.utils import config
    mode = str(config.get_property(f"bigdl.audit.{key}", default)).lower()
    if mode not in _MODES:
        logger.warning("bigdl.audit.%s=%r is not one of %s — pass disabled",
                       key, mode, _MODES)
        return "off"
    return mode


def armed() -> bool:
    """True when at least one audit pass is not ``off`` — the gate the
    compile hook checks before paying for ``lowered.as_text()``."""
    return any(audit_mode(k) != "off" for k in _PASSES)


# ---- StableHLO text census --------------------------------------------------

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8E4M3FN": 1, "f8E5M2": 1, "f8E4M3": 1, "f8E3M4": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
    "i8": 1, "ui8": 1, "i4": 1, "ui4": 1, "i1": 1,
    "complex<f32>": 8, "complex<f64>": 16,
}

_COLLECTIVE_RE = re.compile(
    r'"stablehlo\.(all_reduce|all_gather|reduce_scatter|all_to_all|'
    r'collective_permute|collective_broadcast)"')
_GROUPS_RE = re.compile(
    r'(?:replica_groups|source_target_pairs)\s*=\s*dense<(\[.*?\]|)>')
_FUNC_TYPE_RE = re.compile(r':\s*\(([^()]*)\)\s*->\s*(.+)$')
_TENSOR_RE = re.compile(r'tensor<((?:[^<>]|<[^<>]*>)*)>')
_DIMS_DTYPE_RE = re.compile(r'^((?:\d+x)*)(.+)$')
_OPNAME_RE = re.compile(r'stablehlo\.(\w+)')
_TRANSPOSE_DIMS_RE = re.compile(
    r'stablehlo\.transpose.*?(?:dims|permutation)\s*=\s*(?:dense<)?'
    r'\[([0-9, ]*)\]')
_F64_RE = re.compile(r'\bc?f64\b|complex<f64>')


def _tensor_bytes(spec: str) -> int:
    """Byte size of one ``tensor<...>`` body (``2x4xf32`` → 32; a
    dynamic/unknown dtype estimates at 4 bytes per element)."""
    m = _DIMS_DTYPE_RE.match(spec.strip())
    if m is None:
        return 0
    n = 1
    for d in m.group(1).split("x"):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(m.group(2).strip(), 4)


def _side_bytes(side: str) -> int:
    return sum(_tensor_bytes(t) for t in _TENSOR_RE.findall(side))


@dataclass(frozen=True)
class CollectiveOp:
    """One extracted collective: op name, kind (contract vocabulary),
    operand/result byte totals, the raw type signature, and the replica
    groups / source-target pairs attribute."""

    op: str
    kind: str
    operand_bytes: int
    result_bytes: int
    types: str
    groups: str

    @property
    def traffic_bytes(self) -> int:
        """The per-op budget charge: max(operand, result) — an
        all-gather's cost is its full result, a reduce-scatter's its
        full operand."""
        return max(self.operand_bytes, self.result_bytes)


@dataclass
class ProgramCensus:
    """Everything the parser extracted from one step's StableHLO."""

    label: str
    collectives: List[CollectiveOp] = field(default_factory=list)
    f64_ops: List[str] = field(default_factory=list)
    f32_compute_ops: List[str] = field(default_factory=list)
    transposes: int = 0
    rank4_transposes: int = 0
    peak_bytes: Optional[int] = None

    @property
    def collective_bytes(self) -> int:
        return sum(c.traffic_bytes for c in self.collectives)

    def by_kind(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for c in self.collectives:
            slot = out.setdefault(c.kind, {"ops": 0, "bytes": 0})
            slot["ops"] += 1
            slot["bytes"] += c.traffic_bytes
        return out

    def summary(self) -> Dict[str, Any]:
        """JSON-safe digest — what the compile cache persists in its
        entry manifest (the offline auditor's input) and what the bench
        audit leg records."""
        return {
            "label": self.label,
            "by_kind": self.by_kind(),
            "collective_bytes": self.collective_bytes,
            "transposes": self.transposes,
            "rank4_transposes": self.rank4_transposes,
            "f64_ops": len(self.f64_ops),
            "f32_compute_ops": len(self.f32_compute_ops),
            "peak_bytes": self.peak_bytes,
        }


def parse_stablehlo(label: str, text: str) -> ProgramCensus:
    """One linear scan over the StableHLO text.  Region-bearing
    collectives (``all_reduce``/``reduce_scatter`` carry their reduction
    computation as a region) put their type signature on the closing
    ``})`` line — the scanner tracks region depth to find it."""
    census = ProgramCensus(label=label)
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = lines[i]
        if "stablehlo." not in line:
            i += 1
            continue
        m = _COLLECTIVE_RE.search(line)
        if m:
            op = f"stablehlo.{m.group(1)}"
            kind = m.group(1).replace("_", "-")
            gm = _GROUPS_RE.search(line)
            groups = gm.group(1) if gm else ""
            sig = line
            if _FUNC_TYPE_RE.search(line) is None:
                # region op: chase the closing "}) : (...) -> ..." line
                depth = line.count("({") - line.count("})")
                while depth > 0 and i + 1 < len(lines):
                    i += 1
                    depth += lines[i].count("({") - lines[i].count("})")
                sig = lines[i]
            ft = _FUNC_TYPE_RE.search(sig)
            operand_b = result_b = 0
            types = ""
            if ft:
                operand_b = _side_bytes(ft.group(1))
                result_b = _side_bytes(ft.group(2))
                types = f"({ft.group(1).strip()}) -> {ft.group(2).strip()}"
            census.collectives.append(CollectiveOp(
                op=op, kind=kind, operand_bytes=operand_b,
                result_bytes=result_b, types=types, groups=groups))
            i += 1
            continue
        if _F64_RE.search(line):
            om = _OPNAME_RE.search(line)
            census.f64_ops.append(
                f"stablehlo.{om.group(1) if om else '?'}: {line.strip()}")
        if "stablehlo.dot_general" in line or "stablehlo.convolution" in line:
            ft = _FUNC_TYPE_RE.search(line)
            if ft and any(
                    _DIMS_DTYPE_RE.match(t.strip()) and
                    _DIMS_DTYPE_RE.match(t.strip()).group(2).strip() == "f32"
                    for t in _TENSOR_RE.findall(ft.group(1))):
                om = _OPNAME_RE.search(line)
                census.f32_compute_ops.append(
                    f"stablehlo.{om.group(1)}: "
                    f"({ft.group(1).strip()}) -> {ft.group(2).strip()}")
        if "stablehlo.transpose" in line:
            tm = _TRANSPOSE_DIMS_RE.search(line)
            if tm:
                census.transposes += 1
                if len([d for d in tm.group(1).split(",") if
                        d.strip()]) == 4:
                    census.rank4_transposes += 1
        i += 1
    return census


def peak_buffer_bytes(compiled) -> Optional[int]:
    """Total device footprint estimate from the executable's memory
    analysis: arguments + outputs + temporaries.  Backends (and
    deserialized cache loads) that cannot answer return None — the
    memory pass then only runs its transpose census."""
    try:
        ma = compiled.memory_analysis()
        return int(getattr(ma, "argument_size_in_bytes", 0) +
                   getattr(ma, "output_size_in_bytes", 0) +
                   getattr(ma, "temp_size_in_bytes", 0))
    except Exception:
        return None


# ---- the three pass families ------------------------------------------------


def _check_collectives(census: ProgramCensus,
                       contract: Optional[StepContract]
                       ) -> List[ProgramContractViolation]:
    if contract is None:
        return []
    out: List[ProgramContractViolation] = []
    by_kind = census.by_kind()
    for kind, agg in sorted(by_kind.items()):
        ops = [c for c in census.collectives if c.kind == kind]
        bound = contract.bound_for(kind)
        shapes = "; ".join(c.types or c.op for c in ops[:4])
        if bound is None:
            declared = ", ".join(b.kind for b in contract.collectives) \
                or "none"
            out.append(ProgramContractViolation(
                step=census.label, pass_name="collective", op=ops[0].op,
                detail=f"{agg['ops']} undeclared {kind} op(s) "
                       f"({agg['bytes']} bytes: {shapes}) — the contract "
                       f"declares only: {declared}"))
            continue
        if bound.max_ops is not None and agg["ops"] > bound.max_ops:
            out.append(ProgramContractViolation(
                step=census.label, pass_name="collective", op=ops[0].op,
                detail=f"{agg['ops']} {kind} op(s) exceed the declared "
                       f"max of {bound.max_ops} ({shapes}) — declared "
                       f"for: {bound.reason or 'unspecified'}"))
        if bound.min_ops is not None and agg["ops"] < bound.min_ops:
            out.append(ProgramContractViolation(
                step=census.label, pass_name="collective", op=ops[0].op,
                detail=f"only {agg['ops']} {kind} op(s) where the contract "
                       f"requires at least {bound.min_ops} ({shapes}) — a "
                       f"missing collective means a data range silently "
                       f"skipped its exchange; declared for: "
                       f"{bound.reason or 'unspecified'}"))
        if bound.max_bytes is not None and agg["bytes"] > bound.max_bytes:
            out.append(ProgramContractViolation(
                step=census.label, pass_name="collective", op=ops[0].op,
                detail=f"{kind} traffic {agg['bytes']} bytes exceeds the "
                       f"declared budget of {bound.max_bytes} bytes "
                       f"({shapes})"))
    # a declared kind with an op-count floor that the census does not
    # contain AT ALL never enters the loop above — flag it here (the
    # fully-dropped-collective case)
    for bound in contract.collectives:
        if (getattr(bound, "min_ops", None) and
                bound.min_ops > 0 and bound.kind not in by_kind):
            out.append(ProgramContractViolation(
                step=census.label, pass_name="collective",
                op=f"stablehlo.{bound.kind.replace('-', '_')}",
                detail=f"no {bound.kind} op in the program where the "
                       f"contract requires at least {bound.min_ops} — "
                       f"declared for: {bound.reason or 'unspecified'}"))
    return out


def _check_precision(census: ProgramCensus,
                     contract: Optional[StepContract]
                     ) -> List[ProgramContractViolation]:
    out: List[ProgramContractViolation] = []
    if census.f64_ops:
        out.append(ProgramContractViolation(
            step=census.label, pass_name="precision",
            op=census.f64_ops[0].split(":")[0],
            detail=f"{len(census.f64_ops)} f64 op(s) in the program — "
                   f"x64 drift at execution level (first: "
                   f"{census.f64_ops[0][:160]})"))
    if (contract is not None and contract.activation_dtype == "bf16"
            and census.f32_compute_ops):
        out.append(ProgramContractViolation(
            step=census.label, pass_name="precision",
            op=census.f32_compute_ops[0].split(":")[0],
            detail=f"{len(census.f32_compute_ops)} f32-operand compute "
                   f"op(s) in a program whose declared activation dtype "
                   f"is bf16 (first: {census.f32_compute_ops[0][:160]})"))
    return out


def _check_memory(census: ProgramCensus,
                  contract: Optional[StepContract]
                  ) -> List[ProgramContractViolation]:
    out: List[ProgramContractViolation] = []
    if (contract is not None and
            contract.max_rank4_transposes is not None and
            census.rank4_transposes > contract.max_rank4_transposes):
        out.append(ProgramContractViolation(
            step=census.label, pass_name="memory", op="stablehlo.transpose",
            detail=f"{census.rank4_transposes} rank-4 transposes exceed "
                   f"the declared layout budget of "
                   f"{contract.max_rank4_transposes} — an interior "
                   f"NCHW<->NHWC flip crept back in"))
    return out


# ---- report + entry points --------------------------------------------------


@dataclass
class AuditReport:
    """One audited program: its census, the violations each armed pass
    found, and which of those were found under strict mode."""

    census: ProgramCensus
    violations: List[ProgramContractViolation] = field(default_factory=list)
    strict_violations: List[ProgramContractViolation] = \
        field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def __str__(self):
        c = self.census
        head = (f"program audit [{c.label}]: "
                f"{len(c.collectives)} collective(s) "
                f"({c.collective_bytes} bytes), "
                f"{c.rank4_transposes}/{c.transposes} rank-4 transposes, "
                f"peak {c.peak_bytes if c.peak_bytes is not None else '?'} "
                f"bytes")
        if self.ok:
            return head + " — no violations"
        return "\n".join([head + f" — {len(self.violations)} violation(s)"]
                         + [f"  {v}" for v in self.violations])

    def raise_or_warn(self) -> "AuditReport":
        """Strict-mode findings raise :class:`ProgramContractError`
        (carrying every violation); warn-mode findings log."""
        if self.strict_violations:
            raise ProgramContractError(str(self), self.violations)
        if self.violations:
            logger.warning("%s", self)
        return self


def _export_metrics(census: ProgramCensus) -> None:
    from bigdl_tpu import telemetry
    telemetry.gauge("Audit/collective_bytes",
                    labels={"step": census.label},
                    help="per-step aggregate collective traffic "
                         "(max(operand, result) per op)"
                    ).set(census.collective_bytes)
    for kind, agg in census.by_kind().items():
        telemetry.counter("Audit/collective_ops",
                          labels={"step": census.label, "kind": kind},
                          help="collectives extracted per audited "
                               "program").inc(agg["ops"])
    telemetry.gauge("Audit/rank4_transposes",
                    labels={"step": census.label},
                    help="rank-4 transposes in the audited program"
                    ).set(census.rank4_transposes)
    if census.peak_bytes is not None:
        telemetry.gauge("Audit/peak_bytes", labels={"step": census.label},
                        help="argument+output+temp buffer estimate"
                        ).set(census.peak_bytes)


def audit_step(label: str, hlo_text: str, compiled=None,
               contract: Optional[StepContract] = None,
               topology: Optional[Dict[str, Any]] = None) -> AuditReport:
    """Run every armed pass over one lowered program and return the
    report WITHOUT raising (callers decide via
    :meth:`AuditReport.raise_or_warn` — the compile hook raises after
    the census is safely recorded, the offline CLI never raises).

    ``contract`` defaults to the live/registered contract for
    ``label``; pass ``compiled`` (a jax Compiled/Loaded executable) to
    include the peak-buffer estimate."""
    from bigdl_tpu.analysis import program_contracts
    if contract is None:
        contract = program_contracts.lookup(label)
    census = parse_stablehlo(label, hlo_text)
    if compiled is not None:
        census.peak_bytes = peak_buffer_bytes(compiled)
    report = AuditReport(census=census)
    for pass_key, checker in (("collectives", _check_collectives),
                              ("precision", _check_precision),
                              ("memory", _check_memory)):
        mode = audit_mode(pass_key)
        if mode == "off":
            continue
        found = checker(census, contract)
        report.violations.extend(found)
        if mode == "strict":
            report.strict_violations.extend(found)
    _export_metrics(census)
    if report.violations:
        from bigdl_tpu import telemetry
        for v in report.violations:
            telemetry.counter("Audit/violations",
                              labels={"step": v.step, "pass": v.pass_name},
                              help="program contract violations"
                              ).inc()
    return report


# ---- offline mode over a persisted compile cache ----------------------------


def load_baselines(path: str) -> Dict[str, Dict[str, Any]]:
    with open(path) as f:
        data = json.load(f)
    return data.get("steps", data)


def check_against_baseline(label: str, summary: Dict[str, Any],
                           baseline: Dict[str, Any],
                           bytes_tolerance: float = 1.25,
                           transpose_slack: int = 0) -> List[str]:
    """Regression check of one census summary against its committed
    baseline: collective bytes within ``bytes_tolerance``x, rank-4
    transposes within ``+transpose_slack``, no new collective kind.
    Returns problem strings (empty = within tolerance)."""
    problems: List[str] = []
    base_bytes = baseline.get("collective_bytes", 0)
    if summary.get("collective_bytes", 0) > base_bytes * bytes_tolerance \
            + 1024:
        problems.append(
            f"{label}: collective traffic {summary['collective_bytes']} B "
            f"regressed past {bytes_tolerance}x baseline ({base_bytes} B)")
    base_t = baseline.get("rank4_transposes", 0)
    if summary.get("rank4_transposes", 0) > base_t + transpose_slack:
        problems.append(
            f"{label}: rank-4 transpose census "
            f"{summary['rank4_transposes']} regressed past baseline "
            f"{base_t} (+{transpose_slack} slack)")
    new_kinds = set(summary.get("by_kind", {})) - \
        set(baseline.get("by_kind", {}))
    if new_kinds:
        problems.append(
            f"{label}: new collective kind(s) vs baseline: "
            f"{sorted(new_kinds)}")
    return problems


def audit_cache_dir(path: str, baselines: Optional[Dict[str, Any]] = None
                    ) -> Tuple[List[str], List[str]]:
    """Audit every committed entry of a persisted compile cache from
    its manifest's recorded census (entries stored while the audit was
    armed).  Returns (report_lines, problems) — problems non-empty
    means the offline audit fails."""
    from bigdl_tpu.analysis import program_contracts
    lines: List[str] = []
    problems: List[str] = []
    try:
        names = sorted(os.listdir(path))
    except OSError as e:
        return [], [f"cache dir {path!r} unreadable: {e}"]
    seen = 0
    for name in names:
        if not name.endswith(".commit"):
            continue
        key = name[:-len(".commit")]
        try:
            with open(os.path.join(path, f"{key}.json")) as f:
                manifest = json.load(f)
        except Exception as e:
            problems.append(f"entry {key}: manifest unreadable ({e})")
            continue
        seen += 1
        label = manifest.get("label", "?")
        summary = manifest.get("audit")
        if summary is None:
            lines.append(f"entry {key} [{label}]: no census recorded "
                         "(stored with the audit off) — skipped")
            continue
        contract = program_contracts.lookup(label)
        lines.append(
            f"entry {key} [{label}]: "
            f"{sum(a['ops'] for a in summary.get('by_kind', {}).values())} "
            f"collective(s), {summary.get('collective_bytes', 0)} bytes, "
            f"{summary.get('rank4_transposes', 0)} rank-4 transposes")
        if contract is not None:
            for kind in sorted(summary.get("by_kind", {})):
                if contract.bound_for(kind) is None:
                    problems.append(str(ProgramContractViolation(
                        step=label, pass_name="collective",
                        op=f"stablehlo.{kind.replace('-', '_')}",
                        detail=f"persisted entry {key} contains an "
                               f"undeclared {kind} "
                               f"({summary['by_kind'][kind]['ops']} op(s), "
                               f"{summary['by_kind'][kind]['bytes']} "
                               f"bytes)")))
        if summary.get("f64_ops", 0):
            problems.append(str(ProgramContractViolation(
                step=label, pass_name="precision", op="f64",
                detail=f"persisted entry {key} contains "
                       f"{summary['f64_ops']} f64 op(s)")))
        if baselines is not None and label in baselines:
            problems.extend(check_against_baseline(
                label, summary, baselines[label]))
    if seen == 0:
        lines.append(f"no committed entries under {path!r}")
    return lines, problems


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.analysis.hlo_audit",
        description="offline HLO audit over a persisted compile cache")
    ap.add_argument("cache_dir", help="bigdl.compile.cacheDir to audit")
    ap.add_argument("--baselines", default=None,
                    help="audit_baselines.json to regression-check "
                         "against (optional)")
    args = ap.parse_args(argv)
    baselines = load_baselines(args.baselines) if args.baselines else None
    lines, problems = audit_cache_dir(args.cache_dir, baselines)
    for ln in lines:
        print(ln)
    for p in problems:
        print(f"VIOLATION: {p}")
    print(f"hlo_audit: {len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    import sys
    sys.exit(main(sys.argv[1:]))
