"""Whole-package concurrency soundness pass (the static half).

Run as ``python -m bigdl_tpu.analysis.concurrency <package> [...]``.
Imports nothing heavy (no jax) — safe as a CI / bench preflight, and
wired into ``bench.py --lint-only`` next to the AST linter.

The runtime half is :mod:`bigdl_tpu.analysis.lockwitness`; the two share
one vocabulary: a lock's NAME is the string given to
``analysis.make_lock("...")``, so a static inversion report and a
runtime :class:`~bigdl_tpu.analysis.lockwitness.LockOrderViolation` name
the same nodes.

What one pass over the tree computes
====================================

**Inventory** — every thread entry point (direct
``threading.Thread(target=...)`` construction, ``.spawn(name, fn)`` /
``.submit(fn)`` supervised handoffs, and functions declared with a
``# thread-root`` comment for targets handed across modules, e.g. the
fleet supervisor calling ``Fleet._tick``) and every ``Lock`` / ``RLock``
/ ``Condition`` construction, factory-routed or raw.

**Lock-acquisition-order graph** — which locks can be acquired while
others are held.  ``with`` nesting gives the local edges; a per-module
call-graph approximation (``self.m()`` / bare-name / unique-method
calls, with MAY-held sets propagated caller→callee to a fixpoint)
extends them across call boundaries.  Edges from every module land in
one package-wide graph; a cycle is a potential deadlock.  This
generalizes the old ring-handoff-only ``lock-order`` lint rule to the
whole package.

**Guarded-state contract** — an instance attribute mutated from ≥2
distinct thread roots (spawned roots plus "main": anything reachable
from outside the spawned-root closure) must carry a
``# guarded-by: <lockattr>`` comment on an assignment site, and every
mutation outside ``__init__`` must be syntactically under
``with <that lock>`` — directly, or via MUST-held propagation for
private ``*_locked``-style helpers whose every call site holds the
lock.  Attributes bound to thread-safe primitives (queues, events,
locks, threads) are exempt; ``__init__`` (construction happens-before
publication) is exempt.

**Async-abort safety** — every ``_async_raise`` /
``PyThreadState_SetAsyncExc`` call site must sit under a ``with
<lock>`` whose body re-checks completion (an ``if`` containing a
``return``) before injecting — the discipline all four watchdogs
converged on, now codified: an abort that skips the re-check can kill a
thread that already finished its critical section (the PR 18
mid-admission class of bug).

Rules emitted: ``lock-order-inversion``, ``missing-guarded-by``,
``guarded-mutation-outside-lock``, ``async-abort-unguarded`` (plus
``syntax``).  Findings honor the linter's inline
``# lint: allow(<rule>)`` escape and the shared allowlist file, which
stays empty.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from bigdl_tpu.analysis.lint import (
    DEFAULT_ALLOWLIST, Finding, _inline_allows, _iter_sources,
    load_allowlist)

#: every rule this pass can emit — the CLI validates --rule against it
CONCURRENCY_RULES = frozenset({
    "lock-order-inversion", "missing-guarded-by",
    "guarded-mutation-outside-lock", "async-abort-unguarded", "syntax",
})

#: ``self.X.<m>(...)`` calls that mutate the container X in place
MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "add", "update",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "insert",
    "setdefault",
})

#: constructor names whose result is internally synchronized (or a
#: handle, not shared data): attributes bound to these are exempt from
#: the guarded-state contract
SAFE_CTORS = frozenset({
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "Event",
    "Semaphore", "BoundedSemaphore", "Barrier", "Thread", "local",
    "Lock", "RLock", "Condition", "make_lock", "make_rlock",
    "make_condition", "WitnessLock",
})

#: lock constructors -> kind (for the inventory / witness-name mapping)
LOCK_CTORS = {
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
    "make_lock": "lock", "make_rlock": "rlock",
    "make_condition": "condition",
}

#: names that read as a lock in a ``with`` even without a visible decl
_LOCKISH_RE = re.compile(r"(lock|_cv|cond)$", re.IGNORECASE)
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_THREAD_ROOT_RE = re.compile(r"#\s*thread-root\b")
_ABORT_NAMES = ("_async_raise", "PyThreadState_SetAsyncExc")

#: a lock key is (base, attr): ("self", "_lock") for instance locks,
#: ("", "_NAME_LOCK") for module globals, ("svc", "_lock") for locks
#: reached through a local variable
Key = Tuple[str, str]
#: a function id is (class name or None, dotted qualname within module)
Fid = Tuple[Optional[str], str]


def _mut_target(t: ast.AST) -> Optional[Key]:
    """(base, attr) for ``self.X`` / ``var.X`` assignment targets,
    looking through subscripts and attribute chains to the attribute
    nearest the base name (``self.X[k] = v`` and ``self.X.Y = v`` both
    mutate the object held by ``X``)."""
    while isinstance(t, ast.Subscript):
        t = t.value
    if not isinstance(t, ast.Attribute):
        return None
    node = t
    while isinstance(node.value, (ast.Attribute, ast.Subscript)):
        inner = node.value
        while isinstance(inner, ast.Subscript):
            inner = inner.value
        if not isinstance(inner, ast.Attribute):
            return None
        node = inner
    if isinstance(node.value, ast.Name):
        return (node.value.id, node.attr)
    return None


def _with_key(expr: ast.AST) -> Optional[Key]:
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        return (expr.value.id, expr.attr)
    if isinstance(expr, ast.Name):
        return ("", expr.id)
    return None


def _call_parts(node: ast.Call) -> Tuple[str, Optional[str]]:
    """(base, name) of the callee: ``self.m()`` -> ("self", "m"),
    ``foo()`` -> ("", "foo"), ``obj.m()`` -> ("obj", "m"); anything
    deeper returns (".", None) and is ignored."""
    f = node.func
    if isinstance(f, ast.Name):
        return ("", f.id)
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name):
            return (f.value.id, f.attr)
        return (".", f.attr)
    return (".", None)


def _ctor_name(value: ast.AST) -> Optional[str]:
    if isinstance(value, ast.Call):
        f = value.func
        if isinstance(f, ast.Name):
            return f.id
        if isinstance(f, ast.Attribute):
            return f.attr
    return None


@dataclass
class _ModuleScan:
    """Everything one walk of a module's AST collects; the package-level
    analysis in :func:`analyze` stitches the order graph together and
    judges the contracts."""

    rel: str
    stem: str
    lines: List[str]
    funcs: Dict[Fid, int] = field(default_factory=dict)       # fid -> line
    methods: Dict[Optional[str], Set[str]] = field(default_factory=dict)
    calls: List[Tuple[Fid, str, str, Tuple[Key, ...], int]] = \
        field(default_factory=list)     # (caller, base, name, held, line)
    acquires: List[Tuple[Fid, Tuple[Key, ...], Key, int]] = \
        field(default_factory=list)     # (fid, held-before, key, line)
    mutations: List[Tuple[Key, Optional[str], Fid, Tuple[Key, ...], int]] \
        = field(default_factory=list)   # (target, class ctx, fid, held, line)
    decl_locks: Dict[Tuple[Optional[str], str], Tuple[str, str, int]] = \
        field(default_factory=dict)     # (cls, attr) -> (witness, kind, line)
    safe_attrs: Set[Tuple[Optional[str], str]] = field(default_factory=set)
    annotations: Dict[Tuple[Optional[str], str], Tuple[str, int]] = \
        field(default_factory=dict)     # (cls, attr) -> (lock attr, line)
    spawn_targets: List[Tuple[Optional[str], str, str, str, int]] = \
        field(default_factory=list)     # (cls, scope, base, name, line)
    spawn_sites: List[Tuple[int, str]] = field(default_factory=list)
    declared_roots: Set[Fid] = field(default_factory=set)
    findings: List[Finding] = field(default_factory=list)

    # -- collection walk --------------------------------------------------

    def scan(self, tree: ast.Module) -> None:
        for node in tree.body:
            self._stmt(node, None, "", ())

    def _register(self, cls: Optional[str], scope: str,
                  node: ast.AST) -> Fid:
        name = node.name
        qual = f"{scope}.{name}" if scope else name
        fid = (cls, qual)
        self.funcs[fid] = node.lineno
        self.methods.setdefault(cls, set()).add(name.split(".")[-1])
        line = self.lines[node.lineno - 1] if \
            node.lineno - 1 < len(self.lines) else ""
        if _THREAD_ROOT_RE.search(line):
            self.declared_roots.add(fid)
        return fid

    def _stmt(self, node: ast.AST, cls: Optional[str], scope: str,
              held: Tuple[Key, ...], fid: Optional[Fid] = None,
              withs: Optional[list] = None) -> None:
        withs = withs if withs is not None else []
        if isinstance(node, ast.ClassDef):
            for n in node.body:
                self._stmt(n, node.name, "", ())
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            new_fid = self._register(cls, scope, node)
            new_scope = f"{scope}.{node.name}" if scope else node.name
            for n in node.body:
                # a nested function's body runs when CALLED, not where
                # defined: fresh held stack
                self._stmt(n, cls, new_scope, (), new_fid, [])
            return
        if isinstance(node, ast.With):
            inner = held
            for item in node.items:
                self._exprs(item.context_expr, cls, fid, inner, withs)
                key = _with_key(item.context_expr)
                if key is not None and self._lockish(cls, key):
                    if fid is not None:
                        self.acquires.append((fid, inner, key, node.lineno))
                    inner = inner + (key,)
                    withs = withs + [(key, node)]
            for n in node.body:
                self._stmt(n, cls, scope, inner, fid, withs)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._assign(node, cls, scope, held, fid)
            if node.value is not None:
                self._exprs(node.value, cls, fid, held, withs)
            return
        # generic: visit nested statements with the current context,
        # expressions for calls.  except-handlers and match-cases are
        # NOT ast.stmt but carry statement bodies — recurse into them
        # too, or a `with lock:` inside an `except:` loses its held set
        blockish = (ast.stmt, ast.excepthandler) + (
            (ast.match_case,) if hasattr(ast, "match_case") else ())
        for fname, value in ast.iter_fields(node):
            if isinstance(value, list):
                for v in value:
                    if isinstance(v, blockish):
                        self._stmt(v, cls, scope, held, fid, withs)
                    elif isinstance(v, ast.AST):
                        self._exprs(v, cls, fid, held, withs)
            elif isinstance(value, ast.AST):
                if isinstance(value, blockish):
                    self._stmt(value, cls, scope, held, fid, withs)
                else:
                    self._exprs(value, cls, fid, held, withs)

    def _lockish(self, cls: Optional[str], key: Key) -> bool:
        base, attr = key
        if base == "self" and (cls, attr) in self.decl_locks:
            return True
        if base == "" and (None, attr) in self.decl_locks:
            return True
        return bool(_LOCKISH_RE.search(attr))

    def _assign(self, node: ast.AST, cls: Optional[str], scope: str,
                held: Tuple[Key, ...], fid: Optional[Fid]) -> None:
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        if isinstance(node, ast.AnnAssign) and node.value is None:
            return                       # a bare annotation binds nothing
        flat: List[ast.AST] = []
        for t in targets:
            flat.extend(t.elts if isinstance(t, ast.Tuple) else [t])
        line_text = self.lines[node.lineno - 1] if \
            node.lineno - 1 < len(self.lines) else ""
        ann = _GUARDED_BY_RE.search(line_text)
        for t in flat:
            key = _mut_target(t)
            if key is None:
                continue
            base, attr = key
            owner: Tuple[Optional[str], str]
            if base == "self" and cls is not None:
                owner = (cls, attr)
            elif base == "" :
                owner = (None, attr)
            else:
                owner = (None, attr)    # resolved per-module later
            if not isinstance(node, ast.AugAssign) and base in ("self", ""):
                ctor = _ctor_name(node.value)
                if ctor in LOCK_CTORS:
                    witness = self._witness_name(node.value, owner, ctor)
                    dkey = (cls, attr) if base == "self" else (None, attr)
                    self.decl_locks[dkey] = (
                        witness, LOCK_CTORS[ctor], node.lineno)
                if ctor in SAFE_CTORS:
                    dkey = (cls, attr) if base == "self" else (None, attr)
                    self.safe_attrs.add(dkey)
            if ann and base in ("self", ""):
                akey = (cls, attr) if base == "self" else (None, attr)
                self.annotations.setdefault(
                    akey, (ann.group(1), node.lineno))
            if fid is not None and base != "":
                self.mutations.append((key, cls, fid, held, node.lineno))

    def _witness_name(self, value: ast.Call, owner, ctor: str) -> str:
        if ctor.startswith("make_") and value.args and \
                isinstance(value.args[0], ast.Constant) and \
                isinstance(value.args[0].value, str):
            return value.args[0].value
        cls, attr = owner
        return f"{self.stem}.{cls + '.' if cls else ''}{attr}"

    # -- expression walk (calls, spawns, aborts, mutator methods) ---------

    def _exprs(self, node: ast.AST, cls: Optional[str],
               fid: Optional[Fid], held: Tuple[Key, ...],
               withs: list) -> None:
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            base, name = _call_parts(n)
            if name is None:
                continue
            if fid is not None and base in ("self", "") and \
                    name not in LOCK_CTORS:
                self.calls.append((fid, base, name, held, n.lineno))
            elif fid is not None and base not in ("self", "", "."):
                # obj.m(): resolved later iff m is a method of exactly
                # one class in this module
                self.calls.append((fid, base, name, held, n.lineno))
            # thread entry points
            if name == "Thread":
                tgt = next((kw.value for kw in n.keywords
                            if kw.arg == "target"), None)
                self._spawn(tgt, cls, fid, n.lineno, "Thread")
            elif name in ("spawn", "submit") and n.args:
                arg = n.args[1] if name == "spawn" and len(n.args) > 1 \
                    else n.args[0]
                self._spawn(arg, cls, fid, n.lineno, name)
            # in-place container mutation through a method
            if name in MUTATORS and isinstance(n.func, ast.Attribute):
                key = _mut_target(n.func.value)
                if key is not None and key[0] != "" and fid is not None:
                    self.mutations.append((key, cls, fid, held, n.lineno))
            # async aborts
            if name in _ABORT_NAMES:
                self._abort(n, fid, withs)

    def _spawn(self, tgt: Optional[ast.AST], cls: Optional[str],
               fid: Optional[Fid], line: int, how: str) -> None:
        if isinstance(tgt, ast.Lambda) and isinstance(tgt.body, ast.Call):
            tgt = tgt.body.func
        if isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name):
            base, name = tgt.value.id, tgt.attr
        elif isinstance(tgt, ast.Name):
            base, name = "", tgt.id
        else:
            if how == "Thread":         # dynamic target: inventory only
                self.spawn_sites.append((line, f"{how}(<dynamic>)"))
            return
        scope = fid[1] if fid is not None else ""
        self.spawn_targets.append((cls, scope, base, name, line))
        self.spawn_sites.append((line, f"{how}({base + '.' if base else ''}"
                                       f"{name})"))

    def _abort(self, call: ast.Call, fid: Optional[Fid],
               withs: list) -> None:
        if fid is not None and fid[1].split(".")[-1] == "_async_raise":
            return          # the injector's own internals
        ok = False
        if withs:
            _, with_node = withs[-1]
            for n in ast.walk(with_node):
                if isinstance(n, ast.If) and n.lineno <= call.lineno and \
                        any(isinstance(x, ast.Return) for x in ast.walk(n)):
                    ok = True
                    break
        if not ok:
            self.findings.append(Finding(
                self.rel, call.lineno, "async-abort-unguarded",
                "async abort must re-check completion under the lock the "
                "target sets its done-flag with: wrap the injection in "
                "`with <lock>:` with an `if <done>: return` before it "
                "(see compile_cache/elastic watchdogs), or the abort can "
                "kill a thread that already left its critical section"))

    # -- per-module resolution --------------------------------------------

    def resolve_fn(self, caller: Optional[Fid], base: str,
                   name: str) -> Optional[Fid]:
        if base == "self" and caller is not None:
            fid = (caller[0], name)
            return fid if fid in self.funcs else None
        if base == "":
            if caller is not None:
                # prefer a nested def in the caller's scope chain
                parts = caller[1].split(".")
                for i in range(len(parts), 0, -1):
                    fid = (caller[0], ".".join(parts[:i] + [name]))
                    if fid in self.funcs:
                        return fid
            return (None, name) if (None, name) in self.funcs else None
        # obj.m(): unique-method match across this module's classes
        owners = [c for c, ms in self.methods.items()
                  if c is not None and name in ms and (c, name) in self.funcs]
        if len(owners) == 1:
            return (owners[0], name)
        return None

    def lock_witness(self, cls: Optional[str], key: Key) -> Optional[str]:
        base, attr = key
        if base == "self":
            d = self.decl_locks.get((cls, attr))
            return d[0] if d else None
        if base == "":
            d = self.decl_locks.get((None, attr))
            return d[0] if d else None
        owners = [k for k in self.decl_locks if k[0] is not None and
                  k[1] == attr]
        if len(owners) == 1:
            return self.decl_locks[owners[0]][0]
        return None

    def roots(self) -> Set[Fid]:
        out = set(self.declared_roots)
        for cls, scope, base, name, _line in self.spawn_targets:
            caller = (cls, scope) if scope else None
            fid = self.resolve_fn(caller, base, name)
            if fid is not None:
                out.add(fid)
        return out


def _closure(starts: Iterable[Fid],
             edges: Dict[Fid, Set[Fid]]) -> Set[Fid]:
    seen: Set[Fid] = set(starts)
    work = list(seen)
    while work:
        f = work.pop()
        for g in edges.get(f, ()):
            if g not in seen:
                seen.add(g)
                work.append(g)
    return seen


def _translate(held: Iterable[Key], call_base: str,
               callee_cls: Optional[str]) -> Set[Key]:
    """Map the caller's held keys into the callee's frame: locks reached
    through the call's receiver become the callee's ``self`` locks;
    module-global locks pass through; everything else is dropped (a
    different object's locks mean nothing to the callee)."""
    out: Set[Key] = set()
    for base, attr in held:
        if base == "":
            out.add((base, attr))
        elif base == call_base and callee_cls is not None:
            out.add(("self", attr))
        elif base == "self" and call_base == "self":
            out.add((base, attr))
    return out


def _analyze_module(scan: _ModuleScan,
                    order_edges: List[Tuple[str, str, str, int]]
                    ) -> List[Finding]:
    findings = list(scan.findings)

    # call graph + resolved edges ------------------------------------------
    edges: Dict[Fid, Set[Fid]] = {}
    call_sites: List[Tuple[Fid, Fid, str, Tuple[Key, ...]]] = []
    for caller, base, name, held, _line in scan.calls:
        callee = scan.resolve_fn(caller, base, name)
        if callee is None or callee == caller:
            continue
        edges.setdefault(caller, set()).add(callee)
        call_sites.append((caller, callee, base, held))

    roots = scan.roots()
    spawned = _closure(roots, edges)
    roots_reaching: Dict[Fid, Set[str]] = {}
    for r in roots:
        for f in _closure([r], edges):
            roots_reaching.setdefault(f, set()).add(f"{r[0] or ''}."
                                                    f"{r[1]}".lstrip("."))
    # "main" reaches every function outside the spawned closure, plus
    # anything those call (a public API calling into thread-shared code)
    main_seed = [f for f in scan.funcs if f not in spawned]
    main_reach = _closure(main_seed, edges)

    def _is_private(fid: Fid) -> bool:
        leaf = fid[1].split(".")[-1]
        return leaf.startswith("_") and not leaf.startswith("__")

    # MUST-held at entry (intersection over call sites; fixpoint) ----------
    TOP = None          # "no information yet"
    must: Dict[Fid, Optional[Set[Key]]] = {}
    for f in scan.funcs:
        must[f] = TOP if (_is_private(f) and f not in roots) else set()
    for _ in range(20):
        changed = False
        incoming: Dict[Fid, Optional[Set[Key]]] = {}
        for caller, callee, base, held in call_sites:
            if not (_is_private(callee) and callee not in roots):
                continue
            up = must.get(caller)
            if up is TOP:
                contrib: Optional[Set[Key]] = TOP
            else:
                contrib = _translate(set(held) | up, base, callee[0])
            cur = incoming.get(callee, "unset")
            if cur == "unset":
                incoming[callee] = contrib
            elif contrib is not TOP:
                incoming[callee] = contrib if cur is TOP \
                    else (cur & contrib)
        for f, val in incoming.items():
            if val is not TOP and must[f] != val:
                must[f] = val
                changed = True
        if not changed:
            break
    for f, v in must.items():
        if v is TOP:
            must[f] = set()

    # MAY-held at entry in witness-name space (union; fixpoint) ------------
    may: Dict[Fid, Set[str]] = {f: set() for f in scan.funcs}

    def _names(cls: Optional[str], held: Iterable[Key]) -> Set[str]:
        out = set()
        for k in held:
            w = scan.lock_witness(cls, k)
            if w is not None:
                out.add(w)
        return out

    for _ in range(20):
        changed = False
        for caller, callee, _base, held in call_sites:
            add = may[caller] | _names(caller[0], held)
            if not add <= may[callee]:
                may[callee] |= add
                changed = True
        if not changed:
            break

    # order edges into the package-wide graph ------------------------------
    for fid, held, key, line in scan.acquires:
        inner = scan.lock_witness(fid[0], key)
        if inner is None:
            continue
        outers = _names(fid[0], held) | may[fid]
        for outer in outers:
            if outer != inner:
                order_edges.append((outer, inner, scan.rel, line))

    # guarded-state contract -----------------------------------------------
    # attribute universe: (owner class or None, attr) -> mutation sites
    per_attr: Dict[Tuple[Optional[str], str],
                   List[Tuple[Fid, Tuple[Key, ...], int, str]]] = {}
    class_attrs: Dict[str, Set[Optional[str]]] = {}
    for (base, attr), cls, fid, held, line in scan.mutations:
        if base == "self" and cls is not None:
            class_attrs.setdefault(attr, set()).add(cls)
    for (base, attr), cls, fid, held, line in scan.mutations:
        if base == "self" and cls is not None:
            owner: Tuple[Optional[str], str] = (cls, attr)
        else:
            # var.attr: attributed iff exactly one class in this module
            # owns the attr
            owners = class_attrs.get(attr, set())
            if len(owners) != 1:
                continue
            owner = (next(iter(owners)), attr)
        if owner in scan.safe_attrs or (None, attr) in scan.safe_attrs:
            continue
        if owner in scan.decl_locks:
            continue
        per_attr.setdefault(owner, []).append((fid, held, line, base))

    for owner, sites in sorted(per_attr.items(),
                               key=lambda kv: (kv[0][0] or "", kv[0][1])):
        cls, attr = owner
        ann = scan.annotations.get(owner)
        live = [s for s in sites
                if s[0][1].split(".")[-1] != "__init__"]
        attr_roots: Set[str] = set()
        for fid, _held, _line, _base in live:
            attr_roots |= roots_reaching.get(fid, set())
            if fid in main_reach:
                attr_roots.add("main")
        label = f"{cls}.{attr}" if cls else attr
        if ann is None:
            if len(attr_roots) >= 2:
                first = min(s[2] for s in live)
                findings.append(Finding(
                    scan.rel, first, "missing-guarded-by",
                    f"{label} is mutated from {len(attr_roots)} thread "
                    f"roots ({', '.join(sorted(attr_roots))}) with no "
                    f"`# guarded-by: <lock>` annotation — name the lock "
                    f"on its __init__ assignment and take it at every "
                    f"mutation site"))
            continue
        lock_attr, ann_line = ann
        known = (cls, lock_attr) in scan.decl_locks or \
            (None, lock_attr) in scan.decl_locks
        if not known:
            findings.append(Finding(
                scan.rel, ann_line, "missing-guarded-by",
                f"{label} names guard {lock_attr!r} but no such lock is "
                f"declared in {cls or 'module scope'}"))
            continue
        for fid, held, line, base in live:
            want_base = "self" if base == "self" else base
            effective = set(held)
            if base == "self":
                effective |= must.get(fid, set())
            if (want_base, lock_attr) not in effective and \
                    ("", lock_attr) not in effective:
                findings.append(Finding(
                    scan.rel, line, "guarded-mutation-outside-lock",
                    f"{label} is guarded-by {lock_attr!r} but this "
                    f"mutation is not under `with "
                    f"{base + '.' if base else ''}{lock_attr}` (directly "
                    f"or on every call path)"))
    return findings


def _order_findings(order_edges: List[Tuple[str, str, str, int]]
                    ) -> List[Finding]:
    """Package-wide cycle detection over the static acquisition-order
    graph: 2-cycles are reported pairwise with both witnessing sites;
    longer cycles (rare) report the full chain once."""
    graph: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for outer, inner, rel, line in order_edges:
        graph.setdefault(outer, set()).add(inner)
        sites.setdefault((outer, inner), (rel, line))
    out: List[Finding] = []
    reported: Set[frozenset] = set()
    for a in sorted(graph):
        for b in sorted(graph[a]):
            if a < b and a in graph.get(b, ()):  # 2-cycle, report once
                pair = frozenset((a, b))
                if pair in reported:
                    continue
                reported.add(pair)
                r1, l1 = sites[(a, b)]
                r2, l2 = sites[(b, a)]
                out.append(Finding(
                    r1, l1, "lock-order-inversion",
                    f"{b!r} can be acquired while holding {a!r} here, "
                    f"but {r2}:{l2} acquires {a!r} while holding {b!r} "
                    f"— two threads on these paths can deadlock; pick "
                    f"one order"))
    # longer cycles: DFS from each node not already in a reported pair
    def _cycle_from(start: str) -> Optional[List[str]]:
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start and len(path) > 2:
                    return path + [nxt]
                if nxt not in path:
                    stack.append((nxt, path + [nxt]))
        return None

    for node in sorted(graph):
        cyc = _cycle_from(node)
        if cyc and not any(frozenset((cyc[i], cyc[i + 1])) in reported
                           for i in range(len(cyc) - 1)):
            key = frozenset(cyc)
            if key in reported:
                continue
            reported.add(key)
            rel, line = sites[(cyc[0], cyc[1])]
            out.append(Finding(
                rel, line, "lock-order-inversion",
                f"acquisition-order cycle {' -> '.join(cyc)} — the "
                f"locks on this chain can be taken in a loop across "
                f"threads; break one edge"))
    return out


# ---------------------------------------------------------------------------
# package API
# ---------------------------------------------------------------------------

def analyze(targets: Sequence[str],
            allowlist: Optional[Set[str]] = None) -> List[Finding]:
    allowlist = allowlist or set()
    findings: List[Finding] = []
    order_edges: List[Tuple[str, str, str, int]] = []
    allows_by_rel: Dict[str, Dict[int, Set[str]]] = {}
    for path, rel in _iter_sources(targets):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 0, "syntax",
                                    f"unparseable: {e.msg}"))
            continue
        allows_by_rel[rel] = _inline_allows(source)
        stem = os.path.splitext(os.path.basename(rel))[0]
        scan = _ModuleScan(rel=rel, stem=stem, lines=source.splitlines())
        scan.scan(tree)
        findings.extend(_analyze_module(scan, order_edges))
    findings.extend(_order_findings(order_edges))
    kept = []
    for f in findings:
        if f.rule in allows_by_rel.get(f.path, {}).get(f.line, ()):
            continue
        if f"{f.path}:{f.rule}" in allowlist:
            continue
        kept.append(f)
    return sorted(kept, key=lambda f: (f.path, f.line, f.rule))


def thread_inventory(targets: Sequence[str]) -> dict:
    """The package's concurrency surface: every thread entry point and
    every lock construction site, plus which modules are threaded (the
    ``raw-lock-in-threaded-module`` lint rule's ground truth)."""
    threads: List[dict] = []
    locks: List[dict] = []
    threaded: Set[str] = set()
    for path, rel in _iter_sources(targets):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue
        stem = os.path.splitext(os.path.basename(rel))[0]
        scan = _ModuleScan(rel=rel, stem=stem, lines=source.splitlines())
        scan.scan(tree)
        for line, descr in sorted(scan.spawn_sites):
            threads.append({"file": rel, "line": line, "target": descr})
            threaded.add(rel)
        for fid in sorted(scan.declared_roots,
                          key=lambda f: scan.funcs[f]):
            threads.append({"file": rel, "line": scan.funcs[fid],
                            "target": f"thread-root "
                                      f"{(fid[0] or '') + '.'}{fid[1]}"
                            .lstrip(".")})
            threaded.add(rel)
        for (cls, attr), (witness, kind, line) in sorted(
                scan.decl_locks.items(),
                key=lambda kv: kv[1][2]):
            locks.append({"file": rel, "line": line, "kind": kind,
                          "attr": f"{cls + '.' if cls else ''}{attr}",
                          "name": witness})
    return {"threads": threads, "locks": locks,
            "threaded_modules": sorted(threaded)}


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.analysis.concurrency",
        description="whole-package lock-order / guarded-state / "
                    "async-abort analysis")
    ap.add_argument("targets", nargs="+",
                    help="package directories or .py files")
    ap.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                    help="grandfathered '<relpath>:<rule>' entries "
                         "(default: the in-repo allowlist, kept empty)")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="NAME",
                    help="report only this rule (repeatable); unknown "
                         "names are an error")
    ap.add_argument("--inventory", action="store_true",
                    help="print the thread/lock inventory and exit 0")
    args = ap.parse_args(argv)
    if args.rule:
        unknown = sorted(set(args.rule) - CONCURRENCY_RULES)
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}\n"
                  f"known rules: {', '.join(sorted(CONCURRENCY_RULES))}",
                  file=sys.stderr)
            return 2
    if args.inventory:
        inv = thread_inventory(args.targets)
        for t in inv["threads"]:
            print(f"thread  {t['file']}:{t['line']}  {t['target']}")
        for l in inv["locks"]:
            print(f"lock    {l['file']}:{l['line']}  {l['kind']:9s} "
                  f"{l['attr']}  ->  {l['name']!r}")
        print(f"\n{len(inv['threads'])} thread entry point(s), "
              f"{len(inv['locks'])} lock(s), "
              f"{len(inv['threaded_modules'])} threaded module(s)",
              file=sys.stderr)
        return 0
    findings = analyze(args.targets, load_allowlist(args.allowlist))
    if args.rule:
        findings = [f for f in findings if f.rule in set(args.rule)]
    for f in findings:
        print(f)
    if findings:
        print(f"\n{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
