"""Static analysis + sanitizer passes for the JAX training stack.

The Scala reference enforced its contracts (layout, dtype, threading
discipline) by convention and crashed at runtime when they broke.  The JAX
rebuild makes the three classic failure modes statically and cheaply
detectable, so this package turns them into standing checks instead of
post-mortem archaeology:

1. **Recompile sentinel** (:mod:`~bigdl_tpu.analysis.retrace`) — wraps the
   fused-step ``jax.jit`` entry points with an abstract-signature hash;
   after warmup any retrace raises (strict) or logs a structured
   shape/dtype/weak-type diff (warn), surfaced as ``Analysis/retraces``
   in TrainSummary.
2. **Host-sync guard** (:mod:`~bigdl_tpu.analysis.hostsync`) — a context
   manager around the optimizer hot loop arming JAX transfer guards plus
   instrumented conversion hooks, so implicit device→host pulls (a stray
   ``float()`` / ``np.asarray`` on a device value) fail with the offending
   call-site; intended pulls go through the explicit :func:`host_pull`
   choke point.
3. **Module contract checker** (:mod:`~bigdl_tpu.analysis.contracts`) —
   every ``nn.Module`` may declare an IO contract (ndim, dtype policy);
   :func:`check_model` walks a model with ``jax.eval_shape`` — zero FLOPs —
   and reports contract violations, x64/precision promotion drift, and
   NCHW ops reachable inside an NHWC region.
4. **AST lint** (:mod:`~bigdl_tpu.analysis.lint`,
   ``python -m bigdl_tpu.analysis.lint bigdl_tpu``) — rule-based source
   linter: host syncs in hot-path functions, dtype-dropping ``jnp``
   factories in forward paths under ``nn/``, bare/swallowed exceptions in
   ingest threads, and lock-acquisition-order violations in the ring
   handoffs.  ``tests/test_lint_clean.py`` gates CI on a clean tree.
5. **HLO program auditor** (:mod:`~bigdl_tpu.analysis.hlo_audit` +
   :mod:`~bigdl_tpu.analysis.program_contracts`,
   ``python -m bigdl_tpu.analysis.hlo_audit <cacheDir>``) — static
   passes over every fused step's lowered StableHLO at compile/cache-
   load time: collective contract checker, precision-drift pass, and
   memory/layout budgets.  Modes under ``bigdl.audit.*``.
6. **Concurrency pass** (:mod:`~bigdl_tpu.analysis.concurrency` +
   :mod:`~bigdl_tpu.analysis.lockwitness`,
   ``python -m bigdl_tpu.analysis.concurrency bigdl_tpu``) — the
   static leg inventories thread roots and locks, builds the package-
   wide lock-acquisition-order graph, and enforces the
   ``# guarded-by:`` and async-abort disciplines; the runtime leg is
   the lock factory (:func:`make_lock` / :func:`make_rlock` /
   :func:`make_condition`) whose witness raises a structured
   :class:`LockOrderViolation` on any acquisition-order cycle —
   armed strict for every tier-1 test (``bigdl.analysis.lockWitness``).

Modes per pass (``bigdl.analysis.*`` in ``utils/config.py``): ``strict``
(raise), ``warn`` (log + count), ``off``.
"""

from __future__ import annotations

from bigdl_tpu.utils import config as _config

_MODES = ("strict", "warn", "off")


def pass_mode(key: str, default: str = "warn") -> str:
    """Resolve a pass's mode from ``bigdl.analysis.<key>``; unknown values
    degrade to ``off`` rather than crashing a training run over a typo
    (the typo is still loud: it is logged once)."""
    mode = str(_config.get_property(f"bigdl.analysis.{key}", default)).lower()
    if mode not in _MODES:
        import logging
        logging.getLogger("bigdl_tpu").warning(
            "bigdl.analysis.%s=%r is not one of %s — pass disabled",
            key, mode, _MODES)
        return "off"
    return mode


from bigdl_tpu.analysis.retrace import (RetraceError, RetraceSentinel,  # noqa: E402
                                        abstract_signature)
from bigdl_tpu.analysis.hostsync import (HostSyncError, HostSyncGuard,  # noqa: E402
                                         allow_host_sync, host_pull)
from bigdl_tpu.analysis.contracts import (ContractError, ContractReport,  # noqa: E402
                                          ModuleContract, check_model)
from bigdl_tpu.analysis.program_contracts import (CollectiveBound,  # noqa: E402
                                                  ProgramContractError,
                                                  ProgramContractViolation,
                                                  StepContract)
from bigdl_tpu.analysis.lockwitness import (LockOrderViolation,  # noqa: E402
                                            make_condition, make_lock,
                                            make_rlock)

__all__ = [
    "pass_mode",
    "RetraceError", "RetraceSentinel", "abstract_signature",
    "HostSyncError", "HostSyncGuard", "allow_host_sync", "host_pull",
    "ContractError", "ContractReport", "ModuleContract", "check_model",
    "CollectiveBound", "ProgramContractError", "ProgramContractViolation",
    "StepContract",
    "LockOrderViolation", "make_lock", "make_rlock", "make_condition",
]
