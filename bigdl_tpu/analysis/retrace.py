"""Recompile sentinel: catch silent ``jax.jit`` retraces of the fused step.

A jitted training step recompiles whenever the abstract signature of its
arguments changes — a shape drift from an uneven batch, a dtype flip from a
dropped cast, a weak-type wobble from a Python scalar sneaking into a carry.
Each retrace costs seconds to minutes of XLA compile time and, when it
happens every iteration, silently runs training 3x slow with no error.

The sentinel hashes the abstract signature (pytree structure + per-leaf
shape/dtype/weak-type) of every call to a wrapped step function.  New
signatures during warmup are compiles and are budgeted
(``bigdl.analysis.retraceBudget``); after warmup
(``bigdl.analysis.retraceWarmupSteps`` calls) any unseen signature is a
retrace event: ``strict`` raises :class:`RetraceError` with a structured
per-leaf diff against the previous signature, ``warn`` logs the same diff
and counts it (surfaced as ``Analysis/retraces`` in TrainSummary).

Hashing is host-only metadata work (no device sync): a few hundred
nanoseconds per leaf, irrelevant next to a training step.
"""

from __future__ import annotations

import logging
from typing import Any, List, Optional, Tuple

import numpy as np

from bigdl_tpu.utils import config

logger = logging.getLogger("bigdl_tpu")


class RetraceError(ValueError):
    """A wrapped jitted function was called with an unseen abstract
    signature after warmup.  Subclasses ``ValueError`` so the trainer's
    failure-retry loop treats it as a non-retryable programming error
    (retrying would just recompile again) instead of restoring a
    checkpoint and looping."""


def _leaf_sig(x) -> Tuple:
    """(shape, dtype, weak_type) triple of one argument leaf — the part of
    the abstract value that keys jit's compilation cache."""
    aval = getattr(x, "aval", None)
    if aval is not None:          # jax.Array / tracer
        return (tuple(aval.shape), str(aval.dtype),
                bool(getattr(aval, "weak_type", False)))
    if isinstance(x, np.ndarray):
        return (tuple(x.shape), str(x.dtype), False)
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        # jax.ShapeDtypeStruct (the AOT bucket-precompile path registers
        # abstract variants): signature-identical to the concrete array
        # it stands for
        return (tuple(x.shape), str(x.dtype),
                bool(getattr(x, "weak_type", False)))
    if isinstance(x, (bool, int, float, complex)):
        # python scalars trace as weak-typed 0-d values: the VALUE doesn't
        # retrace, but the TYPE does (int→float flips the weak dtype)
        return ((), type(x).__name__, True)
    # non-array static leaf: identity by repr (strings, None, ...)
    return ("static", repr(x)[:120], False)


def abstract_signature(args: Tuple) -> Tuple[Any, Tuple]:
    """(treedef, per-leaf signature tuple) for a call's positional args —
    equal signatures hit the same jit cache entry."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return treedef, tuple(_leaf_sig(x) for x in leaves)


def _signature_paths(args: Tuple) -> List[str]:
    """Human-readable path per leaf, aligned with the signature tuple."""
    import jax
    flat = jax.tree_util.tree_flatten_with_path(args)[0]
    return [jax.tree_util.keystr(path) for path, _ in flat]


def signature_diff(old: Tuple, new: Tuple, paths: List[str]) -> List[str]:
    """Per-leaf delta lines between two signatures (shape / dtype /
    weak-type changes named explicitly — the reader should not have to
    eyeball two tuples)."""
    old_td, old_sig = old
    new_td, new_sig = new
    lines: List[str] = []
    if old_td != new_td:
        lines.append(f"argument tree structure changed: {old_td} -> {new_td}")
    n = min(len(old_sig), len(new_sig))
    for i in range(n):
        o, nw = old_sig[i], new_sig[i]
        if o == nw:
            continue
        what = []
        if o[0] != nw[0]:
            what.append("shape")
        if o[1] != nw[1]:
            what.append("dtype")
        if o[2] != nw[2]:
            what.append("weak-type")
        path = paths[i] if i < len(paths) else f"leaf[{i}]"
        lines.append(
            f"  {path}: {o[0]} {o[1]}{' weak' if o[2] else ''} -> "
            f"{nw[0]} {nw[1]}{' weak' if nw[2] else ''} "
            f"[{', '.join(what) or 'static'}]")
    if len(old_sig) != len(new_sig):
        lines.append(f"  leaf count changed: {len(old_sig)} -> {len(new_sig)}")
    return lines


class RetraceSentinel:
    """Signature-tracking wrapper around one jitted step function.

    ``wrap(fn)`` returns a callable with identical behaviour plus
    bookkeeping: ``calls``, ``signatures`` (distinct abstract signatures
    seen), ``retraces`` (post-warmup events), ``compiles_in_warmup``, and
    ``last_diff`` (the structured delta of the most recent event).
    """

    def __init__(self, name: str, mode: Optional[str] = None,
                 warmup_steps: Optional[int] = None,
                 budget: Optional[int] = None):
        from bigdl_tpu.analysis import pass_mode
        self.name = name
        self.mode = mode if mode is not None else pass_mode("retrace")
        self.warmup_steps = (warmup_steps if warmup_steps is not None else
                             config.get_int("bigdl.analysis.retraceWarmupSteps",
                                            2))
        self.budget = (budget if budget is not None else
                       config.get_int("bigdl.analysis.retraceBudget", 2))
        self.calls = 0
        self.retraces = 0
        self.compiles_in_warmup = 0
        self._seen = {}            # (treedef, sig) key -> first-seen call no.
        self._last = None          # last (treedef, sig)
        self._last_args_paths: List[str] = []
        self.last_diff: List[str] = []

    @classmethod
    def from_config(cls, name: str) -> Optional["RetraceSentinel"]:
        from bigdl_tpu.analysis import pass_mode
        mode = pass_mode("retrace")
        if mode == "off":
            return None
        return cls(name, mode=mode)

    # -- observation ------------------------------------------------------

    def observe(self, args: Tuple, _key=None) -> Optional[List[str]]:
        """Record one call.  Returns the structured diff when the call is a
        post-warmup retrace (or a warmup compile beyond the budget), else
        None.  ``_key``: the call's precomputed ``abstract_signature``,
        when the wrapper already walked the args (one walk per call)."""
        self.calls += 1
        key = _key if _key is not None else abstract_signature(args)
        hkey = (key[0], key[1])
        if hkey in self._seen:
            self._last = key
            self._last_args_paths = []
            return None
        first = not self._seen
        prev, prev_paths = self._last, self._last_args_paths
        self._seen[hkey] = self.calls
        self._last = key
        self._last_args_paths = _signature_paths(args)
        if first:
            self.compiles_in_warmup += 1
            return None
        in_warmup = self.calls <= self.warmup_steps
        if in_warmup and len(self._seen) <= max(1, self.budget):
            self.compiles_in_warmup += 1
            return None
        paths = prev_paths or self._last_args_paths
        diff = signature_diff(prev, key, paths) if prev is not None else [
            "first signature unavailable"]
        self.last_diff = diff
        self.retraces += 1
        return diff

    def register_warmup(self, args: Tuple) -> None:
        """Record a signature as a WARMUP compile without counting a
        call: the AOT bucket-precompile path
        (``utils/compile_cache.CachedStep``) compiles every configured
        bucket variant ahead of time and registers each here, so a later
        concrete call with a bucketed signature — however deep into the
        run it first appears — is a known compile, never a post-warmup
        retrace.  Idempotent per signature."""
        key = abstract_signature(args)
        hkey = (key[0], key[1])
        if hkey not in self._seen:
            self._seen[hkey] = 0     # pre-registered ahead of any call
            self.compiles_in_warmup += 1

    # -- wrapping ---------------------------------------------------------

    def wrap(self, fn):
        # a tracked CachedStep consumes the same signature this sentinel
        # needs — the argument tree is walked ONCE per call and the key
        # handed to the in-plan pre-check, the observation, and the
        # dispatch
        fast = getattr(fn, "call_with_signature", None)
        # bucket-capable steps pre-register in-plan signatures (an
        # oversize batch rounded to a multiple of the largest bucket is
        # planned work, not a retrace) before this sentinel judges them
        inplan = getattr(fn, "register_if_bucketed", None)

        def wrapped(*args):
            key = (abstract_signature(args)
                   if fast is not None or inplan is not None else None)
            if inplan is not None:
                inplan(args, key)
            diff = self.observe(args, _key=key)
            if diff is not None:
                msg = (
                    f"{self.name}: jitted step retraced at call "
                    f"{self.calls} (signature #{len(self._seen)}, warmup="
                    f"{self.warmup_steps}, budget={self.budget}) — "
                    "signature delta:\n" + "\n".join(diff) +
                    "\nA post-warmup retrace recompiles the fused step "
                    "every occurrence; stabilize the argument signature "
                    "(pad uneven batches, pin dtypes, keep hyper-parameter "
                    "scalars dynamic).  Silence with "
                    "bigdl.analysis.retrace=off.")
                if self.mode == "strict":
                    raise RetraceError(msg)
                logger.warning("%s", msg)
            if fast is not None:
                return fast(args, key)
            return fn(*args)

        wrapped.sentinel = self
        wrapped.__wrapped__ = fn
        return wrapped
