"""Per-step program contracts: what the compiled HLO is ALLOWED to do.

Every fused step family (``local`` / ``local_feval`` / ``shard_map`` /
``gspmd`` / ``pipeline`` / ``eval``) declares a :class:`StepContract` at
construction time and passes it through ``compile_cache.tracked_jit``;
the HLO auditor (:mod:`bigdl_tpu.analysis.hlo_audit`) checks every
lowered program against it at compile (or cache warm-load) time.  A
contract is the program-level counterpart of PR 4's module contracts:
instead of "this layer takes rank-4 float inputs" it says "this step
performs exactly one reduce-scatter over the gradient vector and one
all-gather over the parameter vector, computes in bf16, and nothing
else crosses the interconnect".

The collective vocabulary is the StableHLO one: ``all-reduce`` (psum /
pmean / pmin / pmax all lower here), ``all-gather``, ``reduce-scatter``
(psum_scatter), ``all-to-all`` (MoE expert dispatch), and
``collective-permute`` (ppermute rings — pipeline stages, ring
attention).  An op kind the contract does not declare, or a declared
kind whose aggregate traffic exceeds its byte budget, is a
:class:`ProgramContractViolation` naming the HLO op, its shapes, and
the owning step.

The canonical per-family builders at the bottom are what the trainers
call — each computes its byte bounds from the live model (flat
parameter bytes, module-state bytes), so the budget tightens with the
model instead of being a loose global constant.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: the StableHLO collective vocabulary the auditor extracts
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute",
                    "collective-broadcast")

#: headroom added to computed byte budgets: scalar all-reduces (loss
#: pmean, divergence-verdict pmin) and padding round-off ride under it
SCALAR_SLACK_BYTES = 4096


class ProgramContractError(ValueError):
    """A compiled step violated its program contract (strict mode).
    ``violations`` carries the structured findings."""

    def __init__(self, message: str, violations=None):
        super().__init__(message)
        self.violations = list(violations or [])


@dataclass(frozen=True)
class ProgramContractViolation:
    """One structured audit finding.

    ``step``: the owning fused-step label; ``pass_name``: which audit
    family flagged it (``collective`` / ``precision`` / ``memory``);
    ``op``: the HLO op (``stablehlo.all_gather``, ``stablehlo.
    dot_general``, ...); ``detail``: shapes, byte counts, and the
    violated bound."""

    step: str
    pass_name: str
    op: str
    detail: str

    def __str__(self):
        return (f"[audit/{self.pass_name}] step '{self.step}': {self.op} "
                f"— {self.detail}")


@dataclass(frozen=True)
class CollectiveBound:
    """Budget for one collective kind inside one step's program.

    ``max_ops``: static op-count ceiling (None = any number — e.g. a
    ppermute ring whose op count is a schedule detail); ``min_ops``:
    op-count FLOOR (None = no floor) — a program with fewer ops of the
    kind than declared is as broken as one with more: the bucketed
    ZeRO-1 schedule promises one reduce-scatter and one all-gather PER
    BUCKET, and a silently dropped bucket collective means a parameter
    range trains on unreduced gradients; ``max_bytes``: aggregate
    traffic ceiling over all ops of the kind, where one op's traffic is
    max(operand bytes, result bytes) (None = unbounded); ``reason``:
    why the step legitimately performs this collective — printed with
    violations so the reader sees what WAS declared."""

    kind: str
    max_ops: Optional[int] = None
    max_bytes: Optional[int] = None
    reason: str = ""
    min_ops: Optional[int] = None

    def __post_init__(self):
        if self.kind not in COLLECTIVE_KINDS:
            raise ValueError(
                f"unknown collective kind {self.kind!r} "
                f"(one of {COLLECTIVE_KINDS})")


@dataclass(frozen=True)
class StepContract:
    """The declared program envelope for one fused step family.

    ``collectives``: every collective kind the program may contain,
    each with its budget — a kind absent here is a violation outright.
    ``activation_dtype``: the declared compute precision (``"bf16"`` or
    None = fp32); under bf16 an f32 ``dot_general``/``convolution`` is
    precision drift.  ``max_rank4_transposes``: layout budget — rank-4
    transposes beyond it (boundary NCHW<->NHWC flips are expected, a
    growing interior census is a regressing layout) are a violation;
    None leaves the census uncapped (still exported as a metric)."""

    label: str
    collectives: Tuple[CollectiveBound, ...] = ()
    activation_dtype: Optional[str] = None
    max_rank4_transposes: Optional[int] = None

    def bound_for(self, kind: str) -> Optional[CollectiveBound]:
        for b in self.collectives:
            if b.kind == kind:
                return b
        return None


# ---- registry ---------------------------------------------------------------

#: label -> the most recently declared contract (latest wins: tests build
#: several trainers per process and the audit runs at compile time,
#: immediately after the owning declaration)
_REGISTRY: Dict[str, StepContract] = {}


def declare(contract: StepContract) -> StepContract:
    """Register ``contract`` for its label and return it (what
    ``tracked_jit(..., contract=...)`` calls)."""
    _REGISTRY[contract.label] = contract
    return contract


def lookup(label: str) -> Optional[StepContract]:
    """The live contract declared for ``label`` this process, else the
    canonical default for a known family, else None."""
    c = _REGISTRY.get(label)
    if c is not None:
        return c
    return default_contracts().get(label)


def reset() -> None:
    """Drop live declarations (test isolation)."""
    _REGISTRY.clear()


# ---- canonical per-family builders ------------------------------------------


def local_contract(precision: Optional[str] = None) -> StepContract:
    """Single-process fused train step: everything on one device, no
    interconnect traffic at all."""
    return StepContract(label="local", collectives=(),
                        activation_dtype=precision)


def feval_contract() -> StepContract:
    """Host-driven loss+grad function (LBFGS line search): local and
    fp32-only by construction."""
    return StepContract(label="local_feval", collectives=())


def shard_map_contract(precision: Optional[str], param_bytes: int,
                       state_bytes: int, *, seq_axis: bool = False,
                       expert_axis: bool = False,
                       n_buckets: int = 1,
                       integrity: bool = False) -> StepContract:
    """The ZeRO-1 data-parallel shard_map step: exactly ``n_buckets``
    reduce-scatters over the summed gradient vector, exactly
    ``n_buckets`` all-gathers reassembling the updated weights (the
    latency-hiding overlap schedule partitions the flat vector into
    contiguous buckets; the monolithic baseline is ``n_buckets=1``), and
    a small all-reduce family (loss pmean, module-state pmean per float
    leaf, the divergence-verdict pmin).  The byte budgets do NOT scale
    with ``n_buckets`` — the buckets partition the same vector, so
    aggregate wire traffic is invariant under the bucket count.  The op
    counts are exact both ways (``min_ops == max_ops``): a dropped
    bucket collective means a parameter range silently trains on
    unreduced gradients.  A ``seq``/``expert`` axis adds one full
    gradient psum per extra axis (all-reduce bytes) plus the ring /
    all-to-all exchange the wired layers perform inside the step.

    ``integrity=True`` declares the training-state integrity traffic
    (``bigdl.integrity.everyN`` > 0): exactly ONE extra all-gather — the
    cross-replica fingerprint table exchange
    (``all_reduce.gather_fingerprints``) — plus a few scalar all-reduces
    (the sharded grad-norm psum, the widened verdict pmin) that ride
    under the existing scalar slack.  Declared, not leaked: a
    fingerprint collective the contract does not cover is exactly the
    drift the auditor exists to catch."""
    extra_axes = int(seq_axis) + int(expert_axis)
    fp_gathers = 1 if integrity else 0
    bounds: List[CollectiveBound] = [
        CollectiveBound(
            "reduce-scatter", max_ops=n_buckets, min_ops=n_buckets,
            max_bytes=param_bytes,
            reason="per-bucket gradient sum + shard-scatter "
                   "(arp.reduce_scatter_gradients / "
                   "arp.reduce_scatter_bucket)"),
        CollectiveBound(
            "all-gather", max_ops=n_buckets + fp_gathers,
            min_ops=n_buckets + fp_gathers,
            max_bytes=param_bytes + (SCALAR_SLACK_BYTES if integrity
                                     else 0),
            reason="per-bucket updated-weight reassembly "
                   "(arp.all_gather_weights / arp.all_gather_bucket)"
                   + (" + integrity fingerprint table "
                      "(all_reduce.gather_fingerprints)" if integrity
                      else "")),
        CollectiveBound(
            "all-reduce", max_ops=None,
            # the mstate pmean repeats once per mesh axis the step
            # reduces over (data + each extra axis), the full-gradient
            # psum once per EXTRA axis only
            max_bytes=(state_bytes * (1 + extra_axes) + SCALAR_SLACK_BYTES +
                       param_bytes * extra_axes),
            reason="loss/module-state pmean + divergence pmin"
                   + (" + per-extra-axis gradient psum" if extra_axes
                      else "")),
    ]
    if seq_axis:
        bounds.append(CollectiveBound(
            "collective-permute", reason="ring attention k/v rotation "
                                         "over the seq axis"))
    if expert_axis:
        bounds.append(CollectiveBound(
            "all-to-all", reason="MoE expert dispatch/return over the "
                                 "expert axis"))
    return StepContract(label="shard_map", collectives=tuple(bounds),
                        activation_dtype=precision)


def gspmd_contract(precision: Optional[str] = None) -> StepContract:
    """The dp x tp GSPMD step: the traced program is collective-free —
    gradient all-reduces and tensor-parallel exchanges are inserted by
    XLA's partitioner AFTER StableHLO, so any explicit collective in the
    lowered text is a hand-written stray."""
    return StepContract(label="gspmd", collectives=(),
                        activation_dtype=precision)


def pipeline_contract() -> StepContract:
    """The GPipe step: activations (and their cotangents, in the
    backward the autodiff transpose inserts) rotate around the stage
    ring with collective-permute.  The backward ALSO carries all-reduce:
    the autodiff transpose of values replicated across the stage axis
    (the microbatch input fan-out, the scalar loss) psums their
    cotangents over the ring — empirically 2 activation-sized psums plus
    the scalar loss reduction, a schedule detail whose size tracks the
    microbatch, so the bound declares the kind without a byte cap."""
    return StepContract(label="pipeline", collectives=(
        CollectiveBound("collective-permute",
                        reason="stage-ring activation (and cotangent) "
                               "rotation"),
        CollectiveBound("all-reduce",
                        reason="autodiff-transpose psum of stage-"
                               "replicated values (microbatch cotangents, "
                               "scalar loss)"),))


def eval_contract(sharded: bool = False) -> StepContract:
    """The eval/predict forward: collective-free as traced (the sharded
    variant replicates its output through the GSPMD partitioner, not
    through explicit collectives)."""
    return StepContract(label="eval_sharded" if sharded else "eval",
                        collectives=())


def lm_prefill_contract() -> StepContract:
    """The LM serving prefill step: a single-sequence causal forward
    plus KV-pool scatter, all on one device — collective-free."""
    return StepContract(label="lm_prefill", collectives=())


def lm_decode_contract(label: str = "lm_decode") -> StepContract:
    """The LM serving decode step (``lm_decode`` full-precision /
    ``lm_decode_int8`` quantized-weight tier): one fixed-shape
    batched token step over the paged KV cache, single-device —
    collective-free.  The int8 tier computes its matmuls as
    dequantized f32 contractions (convert + dot), so the precision
    pass's f64 / f32-in-bf16 drift checks apply unchanged — this
    contract is what the quantization gate audits against."""
    return StepContract(label=label, collectives=())


def lm_full_contract() -> StepContract:
    """The LM serving full-forward step (sequential baseline + the
    decode-parity reference): one causal forward, no cache writes,
    collective-free."""
    return StepContract(label="lm_full", collectives=())


def default_contracts() -> Dict[str, StepContract]:
    """Canonical contracts for every known family — what the OFFLINE
    auditor (``python -m bigdl_tpu.analysis.hlo_audit <cacheDir>``)
    checks persisted cache entries against when no live trainer has
    declared byte bounds: kind membership is model-independent, byte
    budgets are not, so the defaults declare kinds with unbounded
    bytes."""
    unbounded = dict(max_ops=None, max_bytes=None)
    return {
        "local": local_contract(),
        "local_feval": feval_contract(),
        "shard_map": StepContract(label="shard_map", collectives=(
            CollectiveBound("reduce-scatter", **unbounded),
            CollectiveBound("all-gather", **unbounded),
            CollectiveBound("all-reduce", **unbounded),
            CollectiveBound("collective-permute", **unbounded),
            CollectiveBound("all-to-all", **unbounded),
        )),
        "gspmd": gspmd_contract(),
        "pipeline": pipeline_contract(),
        "eval": eval_contract(False),
        "eval_sharded": eval_contract(True),
        "lm_prefill": lm_prefill_contract(),
        "lm_decode": lm_decode_contract("lm_decode"),
        "lm_decode_int8": lm_decode_contract("lm_decode_int8"),
        "lm_full": lm_full_contract(),
    }
