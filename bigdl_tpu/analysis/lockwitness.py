"""Runtime lock-order witness: the dynamic half of the concurrency pass.

Every lock the threaded runtime owns is born through the factory choke
point here — :func:`make_lock` / :func:`make_rlock` /
:func:`make_condition` — instead of raw ``threading.Lock()`` (the
``raw-lock-in-threaded-module`` lint rule enforces the routing).  Each
factory lock carries a NAME (a lock *class*: every ``governor.account``
lock shares one node) and a thin wrapper whose acquire path, when the
witness is armed, does what TSan's deadlock detector does at runtime:

- records the per-thread **held-lock stack** (thread-local, no sharing);
- on every acquisition made while other locks are held, adds the
  ``held -> acquiring`` edges to one process-wide **acquisition-order
  graph**, remembering the first witnessing site and stack per edge;
- **before blocking** on the underlying lock, checks whether the new
  edge closes a cycle in that graph — two call paths that take the same
  locks in opposite orders CAN deadlock, whether or not they did this
  run — and raises a structured :class:`LockOrderViolation` naming both
  lock sites and both stacks (strict) or logs it once per edge pair
  (warn).

The check runs before the blocking acquire on purpose: a witness that
only spoke after the acquire would sit silent exactly when the deadlock
it exists to report has already wedged both threads.

Armed STRICT for every tier-1 test by the conftest autouse fixture
(``bigdl.analysis.lockWitness`` + :func:`arm`), exactly like the
host-sync guard; disarmed (the default) every wrapper method is one
module-bool check and a delegate, so production paths pay nanoseconds.
``bench.py --concurrency-only`` asserts the armed per-acquire overhead
stays under 1% of the serving p50.

The chaos injector ``bigdl.chaos.lockDelayAt="<lockname>:k[:seconds]"``
hooks this acquire path: the k-th acquisition of the named lock stalls
for ``seconds`` (default 0.05), deterministically widening a racy
window so an ordering race that needs a lost quantum to bite can be
reproduced on demand (once per position per plan).

The witness's OWN lock is a raw ``threading.Lock`` by design — the
graph guard cannot route through the factory it implements.
"""

from __future__ import annotations

import logging
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

logger = logging.getLogger("bigdl_tpu")

_MODES = ("strict", "warn", "off")

_TLS = threading.local()

_CHAOS_MOD = None


def _chaos():
    """The chaos module, bound once on first armed acquire — a module
    global beats re-running the import machinery on the hot path."""
    global _CHAOS_MOD
    if _CHAOS_MOD is None:
        from bigdl_tpu.utils import chaos
        _CHAOS_MOD = chaos
    return _CHAOS_MOD


def _tls():
    held = getattr(_TLS, "held", None)
    if held is None:
        held = _TLS.held = []
    return held


class LockOrderViolation(RuntimeError):
    """A lock acquisition that closes a cycle in the global
    acquisition-order graph: two call paths take the same locks in
    opposite orders and can deadlock.

    Structured fields (the message carries all of them too):

    - ``edge``: the ``(held, acquiring)`` name pair being added
    - ``reverse_edge``: the previously-recorded conflicting pair
    - ``site`` / ``reverse_site``: ``file:line in func`` of each
      witnessing acquisition
    - ``stack`` / ``reverse_stack``: the full stacks of both
      acquisitions (this thread now; the first witness of the reverse
      edge then)
    """

    def __init__(self, message: str, *, edge: Tuple[str, str],
                 reverse_edge: Tuple[str, str], site: str,
                 reverse_site: str, stack: str, reverse_stack: str):
        super().__init__(message)
        self.edge = edge
        self.reverse_edge = reverse_edge
        self.site = site
        self.reverse_site = reverse_site
        self.stack = stack
        self.reverse_stack = reverse_stack


def _call_site() -> str:
    """``file:line in func`` of the acquiring frame — the first frame
    below this module (the wrapper internals are never the news)."""
    for frame in reversed(traceback.extract_stack()):
        if frame.filename.endswith("lockwitness.py"):
            continue
        return f"{frame.filename}:{frame.lineno} in {frame.name}"
    return "<unknown call site>"


def _stack() -> str:
    frames = [f for f in traceback.extract_stack()
              if not f.filename.endswith("lockwitness.py")]
    return "".join(traceback.format_list(frames))


class _Witness:
    """The process-wide acquisition-order graph + counters.  ``mode`` is
    flipped by :func:`arm`/:func:`disarm`; every wrapper fast-paths on
    it with a single attribute read."""

    def __init__(self):
        self.mode = "off"
        # raw by design: the graph guard cannot route through the
        # factory it implements  # lint: allow(raw-lock-in-threaded-module)
        self._lock = threading.Lock()
        #: outer name -> inner names acquired while outer was held
        self.graph: Dict[str, Set[str]] = {}
        #: (outer, inner) -> (site, stack) of the first witnessing acquire
        self.edge_sites: Dict[Tuple[str, str], Tuple[str, str]] = {}
        #: per-name acquisition counters for the ONE lock an armed
        #: chaos lockDelayAt plan targets (exact, locked); all other
        #: names never enter this dict
        self.name_counts: Dict[str, int] = {}
        #: every name witnessed at least once (unlocked; GIL-atomic add)
        self.names: Set[str] = set()
        #: witness-lock name an armed chaos lockDelayAt plan targets
        #: (pushed by chaos.install/uninstall) — one attribute compare
        #: on the hot path instead of a chaos probe per acquisition
        self.chaos_target: Optional[str] = None
        self.acquires = 0
        self.violations = 0
        self._warned: Set[Tuple[str, str]] = set()
        #: arming generation: bumped by arm(); per-thread held stacks
        #: tagged with an older generation are stale (their locks were
        #: released while the witness was off) and get dropped lazily
        self.gen = 0

    # -- graph -----------------------------------------------------------

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """A directed path src -> ... -> dst in the current graph, or
        None.  Iterative DFS; the graph is dozens of nodes, not
        thousands."""
        if src == dst:
            return [src]
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self.graph.get(node, ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def record_edge(self, outer: str, inner: str
                    ) -> Optional[LockOrderViolation]:
        """Add ``outer -> inner``; returns the violation when the edge
        closes a cycle (caller raises/logs per mode).  Site/stack capture
        happens only for NEW edges, so steady-state cost is one dict
        probe under the witness lock."""
        with self._lock:
            known = self.graph.get(outer)
            if known is not None and inner in known:
                return None
            cycle = self._path(inner, outer)
            self.graph.setdefault(outer, set()).add(inner)
            site, stack = _call_site(), _stack()
            self.edge_sites[(outer, inner)] = (site, stack)
            if cycle is None:
                return None
            self.violations += 1
            # the first edge of the recorded reverse path is the other
            # half of the inversion: inner -> ... -> outer
            rev = (cycle[0], cycle[1])
            rev_site, rev_stack = self.edge_sites.get(
                rev, ("<unknown>", "<no stack recorded>"))
        chain = " -> ".join(cycle)
        msg = (
            f"lock-order inversion: acquiring {inner!r} while holding "
            f"{outer!r} at {site}, but the acquisition-order graph "
            f"already records {chain} -> {outer} (edge {rev[0]!r} -> "
            f"{rev[1]!r} first witnessed at {rev_site}) — two threads "
            f"taking these paths concurrently can deadlock.\n"
            f"--- this acquisition ({outer} -> {inner}) ---\n{stack}"
            f"--- prior acquisition ({rev[0]} -> {rev[1]}) ---\n"
            f"{rev_stack}")
        return LockOrderViolation(
            msg, edge=(outer, inner), reverse_edge=rev, site=site,
            reverse_site=rev_site, stack=stack, reverse_stack=rev_stack)

    # -- acquire/release hooks ------------------------------------------

    def scan_held(self, lock: "WitnessLock", held: list) -> None:
        """The nested-acquisition path (something else already held):
        record ``held -> acquiring`` edges, raise/log on a cycle."""
        if any(h is lock for h in held):       # reentrant: no new edges
            return
        for h in held:
            if h.name == lock.name:
                continue   # same lock class nested: no self-edges
            violation = self.record_edge(h.name, lock.name)
            if violation is not None:
                if self.mode == "strict":
                    raise violation
                pair = tuple(sorted(violation.edge))
                with self._lock:
                    fresh = pair not in self._warned
                    self._warned.add(pair)
                if fresh:
                    logger.warning("%s", violation)

    def chaos_delay(self, lock: "WitnessLock") -> None:
        """The chaos-targeted path: per-name exact counting (the plan's
        k counts acquisitions since the plan was armed) + the stall."""
        with self._lock:
            n = self.name_counts.get(lock.name, 0) + 1
            self.name_counts[lock.name] = n
        delay = _chaos().lock_delay(lock.name, n)
        if delay > 0:
            import time
            time.sleep(delay)

    # -- introspection ----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "mode": self.mode,
                "acquires": self.acquires,
                "locks": len(self.names),
                "edges": sum(len(v) for v in self.graph.values()),
                "violations": self.violations,
            }

    def reset(self) -> None:
        with self._lock:
            self.graph.clear()
            self.edge_sites.clear()
            self.name_counts.clear()
            self.names.clear()
            self.acquires = 0
            self.violations = 0
            self._warned.clear()


_WITNESS = _Witness()


class WitnessLock:
    """Factory lock: a named wrapper over a raw ``threading.Lock`` /
    ``RLock``.  Disarmed, every method is one mode check + delegate;
    armed, the acquire path runs the witness (see module doc)."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str, raw):
        self.name = name
        self._lock = raw

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        w = _WITNESS
        if w.mode == "off":
            return self._lock.acquire(blocking, timeout)
        # armed fast path, inlined flat: counters are unlocked on purpose
        # (a lost increment under the GIL is telemetry drift; the graph
        # itself stays guarded — record_edge takes the witness lock) and
        # the nested/chaos branches are out-of-line — an uncontended
        # leaf acquire pays attribute reads, not function calls
        held = getattr(_TLS, "held", None)
        if held is None:
            held = _TLS.held = []
        if getattr(_TLS, "gen", -1) != w.gen:
            del held[:]                 # stale entries from a prior window
            _TLS.gen = w.gen
        w.acquires += 1
        w.names.add(self.name)                 # set.add is GIL-atomic
        if held:
            w.scan_held(self, held)     # may raise LockOrderViolation
        if w.chaos_target is not None and w.chaos_target == self.name:
            w.chaos_delay(self)
        got = self._lock.acquire(blocking, timeout)
        if got:
            held.append(self)
        return got

    def release(self) -> None:
        self._lock.release()
        if _WITNESS.mode != "off":
            held = getattr(_TLS, "held", None)
            if held:                 # disarmed-acquired: nothing tracked
                for i in range(len(held) - 1, -1, -1):
                    if held[i] is self:
                        del held[i]
                        break

    def __enter__(self) -> bool:
        if _WITNESS.mode == "off":     # skip the wrapper layer entirely
            return self._lock.acquire()
        return self.acquire()

    def __exit__(self, *exc) -> None:
        if _WITNESS.mode == "off":
            self._lock.release()
            return
        self.release()

    def locked(self) -> bool:
        fn = getattr(self._lock, "locked", None)
        return bool(fn()) if fn is not None else False

    def __repr__(self) -> str:
        return f"<WitnessLock {self.name!r} over {self._lock!r}>"


def make_lock(name: str) -> WitnessLock:
    """A named, witnessed mutual-exclusion lock — the factory every
    threaded module routes ``threading.Lock()`` through."""
    return WitnessLock(name, threading.Lock())


def make_rlock(name: str) -> WitnessLock:
    """A named, witnessed reentrant lock.  Reentrant acquisitions are
    recognized by object identity on the held stack and add no edges."""
    return WitnessLock(name, threading.RLock())


def make_condition(name: str) -> threading.Condition:
    """A condition variable over a witnessed (non-reentrant) factory
    lock.  ``wait()`` releases and re-acquires through the wrapper, so
    the held-lock stack stays truthful across waits.  Always a plain
    underlying Lock: ``threading.Condition``'s ownership probe
    (``acquire(False)``) is only correct for non-reentrant locks."""
    return threading.Condition(make_lock(name))


# ---------------------------------------------------------------------------
# arming (the conftest autouse fixture's surface)
# ---------------------------------------------------------------------------

def arm(mode: Optional[str] = None) -> str:
    """Arm the witness: ``strict`` raises :class:`LockOrderViolation` on
    any cycle, ``warn`` logs once per edge pair and counts.  ``mode``
    None resolves ``bigdl.analysis.lockWitness`` (default ``off``).
    Returns the effective mode."""
    if mode is None:
        from bigdl_tpu.analysis import pass_mode
        mode = pass_mode("lockWitness", default="off")
    if mode not in _MODES:
        logger.warning("lockwitness: unknown mode %r — staying off", mode)
        mode = "off"
    if _WITNESS.mode == "off" and mode != "off":
        _WITNESS.gen += 1        # new arming window: stale held entries
        #                          (released while off) must not survive
    _WITNESS.mode = mode
    return mode


def set_chaos_delay_target(name: Optional[str]) -> None:
    """Called by ``chaos.install``/``uninstall``: the witness lock name
    an armed ``bigdl.chaos.lockDelayAt`` plan targets (None to clear)."""
    _WITNESS.chaos_target = name


def disarm() -> None:
    """Back to free-running (plain delegation); the recorded graph is
    kept — call :func:`reset` for test isolation."""
    _WITNESS.mode = "off"


def armed() -> str:
    return _WITNESS.mode


def snapshot() -> dict:
    """Witness counters: acquires, distinct locks, edges, violations."""
    return _WITNESS.snapshot()


def reset() -> None:
    """Drop the acquisition-order graph and all counters (test
    isolation between arming windows)."""
    _WITNESS.reset()


def order_graph() -> Dict[str, Set[str]]:
    """A copy of the current acquisition-order graph (diagnostics)."""
    with _WITNESS._lock:
        return {k: set(v) for k, v in _WITNESS.graph.items()}
