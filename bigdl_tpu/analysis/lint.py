"""Rule-based AST linter for the bigdl_tpu source tree.

Run as ``python -m bigdl_tpu.analysis.lint <path-or-package> [...]``.
Imports nothing heavy (no jax), so it is safe as a CI / bench preflight.

Rules
=====

``host-sync-in-hot-path``
    In hot-loop functions (``drain`` / ``run_step`` / ``shard_step`` /
    ``step`` under ``optim/``, ``parallel/``, ``engine.py``), calls that
    force an implicit device→host sync — ``float(x)`` / ``int(x)`` /
    ``bool(x)`` / ``np.asarray`` / ``np.array`` / ``.item()`` /
    ``.tolist()`` on non-literal arguments.  Route pulls through
    ``bigdl_tpu.analysis.host_pull`` (calls wrapping a ``host_pull``
    result are exempt).

``raw-clock-in-hot-path``
    In hot-loop functions (``drain`` / ``run_step`` / ``shard_step`` /
    ``step``) anywhere outside the telemetry package, direct reads of a
    raw timer — ``time.time()`` / ``time.time_ns()`` /
    ``time.perf_counter[_ns]()`` / ``time.monotonic[_ns]()``.  The
    telemetry clock (``bigdl_tpu.telemetry.clock_ns``) is the ONE hot-
    path timer: every duration lands on a single monotonic timeline, so
    span traces, step decomposition, and subsystem counters always
    compare.

``jnp-dtype-drop``
    Under ``nn/``, inside forward-path functions (``apply`` and the
    recurrent forward helpers ``init_hidden`` / ``project_input`` /
    ``step`` / ``route`` / ``expert_forward``), ``jnp.zeros`` /
    ``jnp.ones`` / ``jnp.empty`` with no dtype argument: the float32
    default silently promotes a bf16 forward back to full precision.
    (``jnp.full`` inherits its fill value's dtype and ``jnp.arange``
    defaults to integer indices — both excluded.)

``bare-except``
    ``except:`` with no exception class, anywhere: it swallows
    ``KeyboardInterrupt``/``SystemExit`` and hides real faults.

``swallowed-exception``
    In the threaded ingest/engine files (``dataset/ingest.py``,
    ``engine.py``), an ``except Exception``/``BaseException`` handler
    whose whole body is ``pass``/``continue``: a worker thread that eats
    its own failure starves the pipeline with no diagnostic.  Narrow the
    class (``queue.Full``/``queue.Empty``) or surface the error.

``lock-order``
    Across ``dataset/ingest.py`` + ``engine.py``, nested ``with <lock>``
    acquisitions are collected into a lock-order graph (locks identified
    by attribute/global name); a cycle means two call paths can acquire
    the same pair of locks in opposite orders — the classic ring-handoff
    deadlock.

``blocking-under-lock``
    Same files: a blocking call (``.put(...)`` / ``.get(...)`` /
    ``.join(...)`` / ``time.sleep`` / ``wait``) while holding a lock —
    the handoff rings must never be touched under a stage lock.

``signal-handler-in-hot-path``
    In hot-loop functions (the ``host-sync`` set plus the driver's
    ``_drive`` / the watchdog ``heartbeat`` / the chaos ``on_step``
    hooks), calls into the ``signal`` module — ``signal.signal`` /
    ``signal.getsignal`` / ``signal.setitimer`` / masking.  Handler
    (de)installation belongs at run scope
    (``elastic.PreemptionHandler``): per-iteration signal syscalls cost
    real time, and a handler swapped inside the loop can lose the one
    SIGTERM the scheduler will ever send.

``untracked-jit``
    Anywhere in the package outside the registered cache wrapper
    (``utils/compile_cache.py``): a ``jax.jit``/``jax.pjit`` call (or
    decorator), a ``.lower(...)`` call with arguments, or a no-argument
    ``.compile()`` call.  An untracked jit entry point compiles outside
    the persistent executable cache, the AOT warmup phase, and the
    compile watchdog — a cold start it silently re-pays every process
    and a wedge nothing supervises.  Route fused steps through
    ``compile_cache.tracked_jit``; genuinely-exempt sites (debug shells,
    cost-analysis lowerings) carry an inline
    ``# lint: allow(untracked-jit)`` with the reason.

``unbounded-queue-in-serving``
    In the serving package (``bigdl_tpu/serving/``) and the threaded
    engine file (``engine.py``): a ``queue.Queue()`` /
    ``queue.SimpleQueue()`` / ``collections.deque()`` constructed
    without a bound (no ``maxsize=``/``maxlen=``, or an explicit
    0/None).  An unbounded ring on the request path turns overload into
    silent memory growth and unbounded tail latency — the admission
    controller is the ONE place allowed to say no, and it can only do
    that if every queue behind it is bounded.  (``SimpleQueue`` cannot
    be bounded at all and always flags.)

``unbounded-decode-loop``
    In the LM token-serving file (``bigdl_tpu/serving/lm.py``): any
    ``while`` whose test is a bare constant (``while True``) or whose
    test expression references no name/attribute matching
    ``max|deadline|remaining|budget|bound|stop|drain|terminal``
    (case-insensitive).  An autoregressive decode loop with no
    max-steps/deadline bound is the serving equivalent of an unbounded
    queue: one sequence that never emits EOS wedges its slot (and its
    KV blocks) forever, and no drain can ever finish.  Every generation
    loop must be a bounded ``for`` or test a budget/deadline/terminal
    condition.  The allowlist stays empty.

``unguarded-io-in-stage-thread``
    In the ingest stage-thread file (``dataset/ingest.py``), raw file IO
    — builtin ``open(...)`` / ``os.open`` / ``io.open`` / an
    ``fsspec.open`` — anywhere in the module.  Stage threads re-raise at
    the consumer, so an unguarded read that hits a transient storage
    blip aborts the whole training run; every byte the pipeline touches
    must route through ``utils.file_io`` (the capped-backoff retry +
    chaos choke point) or ``dataset.seqfile`` (the corrupt-record
    taxonomy + resync), or carry an explicit
    ``# lint: allow(unguarded-io-in-stage-thread)``.

``unaccounted-buffer-in-stage``
    In the stage/serving files (``dataset/ingest.py``, ``engine.py``,
    ``bigdl_tpu/serving/``): a batch-scale host allocation —
    ``np.empty``/``np.zeros``/``bytearray`` sized by a
    ring/batch/depth-scale expression — in a scope with no
    resource-governor accounting.  Every bounded buffer these paths own
    must charge a ``bigdl_tpu.resources.GOVERNOR`` account (via
    ``account().add``/``item_nbytes``/``check_item``), or the
    ``Resources/host_bytes`` roll-up and the host-memory budget it
    enforces under-report by exactly that buffer.  The allowlist stays
    empty.

``undeclared-collective``
    In the trainer step-constructor files (``optim/optimizer.py`` /
    ``optim/evaluator.py`` / ``optim/predictor.py`` /
    ``parallel/distri_optimizer.py`` / ``parallel/pipeline.py``), raw
    collective calls — ``lax.psum`` / ``psum_scatter`` / ``pmean`` /
    ``pmin`` / ``pmax`` / ``ppermute`` / ``all_gather`` / ``all_to_all``
    / ``pbroadcast`` (``lax.axis_index`` is positional, not a
    collective, and exempt).  The AST-level companion to the HLO
    auditor's collective contract pass: every collective a step body
    performs must go through the declared-contract helpers in
    ``parallel/all_reduce.py`` (``axis_sum`` / ``axis_mean`` /
    ``axis_min`` / ``ring_permute`` / ``pmean_floats`` /
    ``AllReduceParameter``), so the declared contract and the source
    stay greppably in sync.  The allowlist stays empty.

``host-augment-in-hot-path``
    In the dataset package's hot-path modules (``bigdl_tpu/dataset/``),
    per-pixel host augmentation calls — ``cv2.resize`` / ``cv2.flip`` /
    ``cv2.warpAffine`` / ``cv2.cvtColor`` / ``cv2.normalize`` & co.,
    ``np.flip`` / ``fliplr`` / ``flipud`` / ``rot90``, or a PIL-style
    ``.crop(...)`` method call.  The real-data hot path ships raw uint8
    frames and runs crop/flip/normalize/ColorJitter on device
    (``nn.DeviceAugment`` + ``dataset/device_augment.py``) — host
    augmentation silently drifting back in re-pins the decode pool as
    the bottleneck.  The DECLARED host-fallback modules are exempt:
    ``dataset/image.py`` (the reference host transformer library) and
    ``dataset/mt_batch.py`` (the synchronous MT path + the mixed-shape
    pre-crop fallback).  (``cv2.imdecode`` is decode, not augmentation,
    and is never flagged.)

``unsupervised-thread-in-fleet``
    In the fleet control plane (``bigdl_tpu/fleet/``), a raw
    ``threading.Thread(...)`` construction anywhere outside
    ``FleetSupervisor.spawn``.  Every fleet thread must be born through
    the supervisor so fleet stop can drain it and diagnostics can
    enumerate it — a thread the supervisor cannot see is a thread a
    chaos test cannot prove anything about.  The one legitimate
    construction site (inside ``spawn`` itself) carries the inline
    allow; the allowlist stays empty.

``untraced-terminal-verdict``
    In the serving and fleet packages (``bigdl_tpu/serving/``,
    ``bigdl_tpu/fleet/``): a ``raise`` that constructs a terminal
    serving-taxonomy error (``Overloaded`` / ``DeadlineExceeded`` /
    ``ServingDataError`` / ``HungDispatchError`` / ``ReplicaKilled``)
    — directly or via a name bound from one — anywhere outside the
    verdict choke points, or a raw terminal transition
    (``req._finish(...)`` / ``stream._finish(...)``) outside the
    accounting chokes.  Every terminal error must flow through a choke
    that stamps ``request_trace.verdict`` (the validation chokes
    ``_validate``/``_decode``, the KV-pool admission answer
    ``allocate``, the offline ``generate`` paths where no admitted
    request exists, or a ``_reject_locked``-style rejection minter);
    every finish must flow through ``_account``/``_finish_stream``/
    ``abandon``.  A request that dies outside the chokes is a request
    whose trace never says why — the exact failure mode the forensic
    layer exists to make impossible.  The allowlist stays empty.

Silencing: append ``# lint: allow(<rule-name>)`` to the offending line,
or list ``<relpath>:<rule-name>`` in an allowlist file (one per line,
``#`` comments) — the CI gate keeps the repo allowlist empty, so every
grandfathered site is visible in the diff that introduces it.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

HOT_FUNCS = {"drain", "run_step", "shard_step", "step"}
HOT_SCOPES = (os.path.join("optim", ""), os.path.join("parallel", ""),
              "engine.py")
SYNC_BUILTINS = {"float", "int", "bool"}
SYNC_NP = {"asarray", "array", "float32", "float64"}
SYNC_METHODS = {"item", "tolist"}

RAW_CLOCKS = {"time", "time_ns", "perf_counter", "perf_counter_ns",
              "monotonic", "monotonic_ns"}
TELEMETRY_SCOPE = os.path.join("telemetry", "")

#: signal-module calls that must stay out of per-iteration code; the
#: hot set widens to the driver loop body and the elastic hooks it calls
SIGNAL_CALLS = {"signal", "getsignal", "setitimer", "sigwait",
                "pthread_sigmask", "pthread_kill", "raise_signal"}
SIGNAL_HOT_FUNCS = HOT_FUNCS | {"_drive", "heartbeat", "on_step"}

NN_SCOPE = os.path.join("nn", "")
FORWARD_FUNCS = {"apply", "init_hidden", "project_input", "step", "route",
                 "expert_forward"}
DTYPE_DROP_FACTORIES = {"zeros", "ones", "empty"}

#: the ONE registered jit wrapper: jax.jit/.lower()/.compile() live here
TRACKED_JIT_FILES = (os.path.join("utils", "compile_cache.py"),)
JIT_NAMES = {"jit", "pjit"}

THREADED_FILES = (os.path.join("dataset", "ingest.py"), "engine.py")
#: the serving request path: every queue/ring here must be bounded (the
#: admission controller is the only place allowed to say no)
SERVING_SCOPE = os.path.join("serving", "")
QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue"}
#: files whose threads feed the training loop: raw file IO here must
#: route through utils.file_io / dataset.seqfile (retry + taxonomy)
STAGE_THREAD_FILES = (os.path.join("dataset", "ingest.py"),)
RAW_IO_QUALIFIERS = {"os", "io", "fsspec"}
BLOCKING_METHODS = {"put", "get", "join", "wait", "sleep", "acquire"}
#: receivers whose .put/.get actually block (queues/rings) — a dict .get
#: or os.environ.get under a lock is not a handoff
_QUEUEISH = re.compile(r"(^q$|_q$|queue|ring)", re.IGNORECASE)

#: trainer step-constructor files: every collective a step body performs
#: must route through the declared-contract helpers in
#: parallel/all_reduce.py (the HLO audit contract's source-level mirror)
TRAINER_STEP_FILES = (os.path.join("optim", "optimizer.py"),
                      os.path.join("optim", "evaluator.py"),
                      os.path.join("optim", "predictor.py"),
                      os.path.join("parallel", "distri_optimizer.py"),
                      os.path.join("parallel", "pipeline.py"))
#: raw lax collectives (axis_index is positional lookup, not traffic)
COLLECTIVE_CALLS = {"psum", "psum_scatter", "pmean", "pmin", "pmax",
                    "ppermute", "all_gather", "all_to_all", "pbroadcast"}

#: stage/serving files whose host buffers must be governor-accounted —
#: a batch-scale allocation invisible to Resources/host_bytes makes the
#: host-memory budget a lie
ACCOUNTED_BUFFER_FILES = (os.path.join("dataset", "ingest.py"),
                          "engine.py")
#: size expressions built from these name fragments are pipeline-scale
#: (depth x batch), not scalar temps
_BUFFER_SCALE = re.compile(
    r"(batch|ring|depth|maxsize|ahead|queue|window|slot)", re.IGNORECASE)
BUFFER_CTORS_NP = {"empty", "zeros"}
#: calls that mark the enclosing scope as governor-accounted
ACCOUNTING_CALLS = {"account", "item_nbytes", "check_item", "_charge",
                    "_slot_nbytes"}

#: dataset hot-path scope for the host-augmentation rule; the declared
#: host-fallback modules (reference host transformer library + the
#: synchronous MT path with its mixed-shape pre-crop) are exempt
DATASET_SCOPE = os.path.join("dataset", "")
FLEET_SCOPE = os.path.join("fleet", "")

#: the terminal serving-taxonomy errors: a request that dies with one of
#: these must die through a verdict-recording choke point
TERMINAL_ERRORS = {"Overloaded", "DeadlineExceeded", "ServingDataError",
                   "HungDispatchError", "ReplicaKilled"}
#: (rel-path suffix, function) pairs allowed to construct-and-raise a
#: terminal error: validation chokes whose callers account the verdict,
#: the KV-pool admission answer, and the offline generate paths (no
#: admitted request exists to trace).  Rejection minters
#: (``_reject_locked`` / ``_fleet_reject``) RETURN the error after
#: stamping the trace, so their raise sites never match the pattern.
VERDICT_RAISE_CHOKES = {
    (os.path.join("serving", "engine.py"), "_decode"),
    (os.path.join("serving", "lm.py"), "_validate"),
    (os.path.join("serving", "lm.py"), "generate"),
    (os.path.join("serving", "lm.py"), "generate_sequential"),
    (os.path.join("serving", "kv_cache.py"), "allocate"),
}
#: functions allowed to drive the raw terminal transition ``._finish()``:
#: the accounting chokes that stamp request_trace.verdict + exemplars
VERDICT_FINISH_CHOKES = {
    (os.path.join("serving", "engine.py"), "_account"),
    (os.path.join("serving", "engine.py"), "abandon"),
    (os.path.join("serving", "lm.py"), "_finish_stream"),
}
HOST_AUGMENT_FALLBACK_FILES = (os.path.join("dataset", "image.py"),
                               os.path.join("dataset", "mt_batch.py"))
#: per-pixel augmentation calls that belong on device (nn.DeviceAugment)
HOST_AUGMENT_CV2 = {"resize", "flip", "warpAffine", "warpPerspective",
                    "cvtColor", "GaussianBlur", "copyMakeBorder",
                    "normalize", "rotate"}
HOST_AUGMENT_NP = {"flip", "fliplr", "flipud", "rot90"}
HOST_AUGMENT_METHODS = {"crop"}         # PIL Image.crop

#: directories whose locks stay raw by design: the witness itself and the
#: telemetry it reports through must not route their own locks back into
#: the witness (self-observation cycle / import cycle with analysis)
RAW_LOCK_EXEMPT_DIRS = (os.path.join("analysis", ""),
                        os.path.join("telemetry", ""))
LOCK_CTORS = {"Lock", "RLock", "Condition"}

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([a-z0-9_,\- ]+)\)")

#: every rule the linter can emit — the CLI validates --rule against it
KNOWN_RULES = frozenset({
    "host-sync-in-hot-path", "raw-clock-in-hot-path",
    "signal-handler-in-hot-path", "jnp-dtype-drop", "untracked-jit",
    "undeclared-collective", "unguarded-io-in-stage-thread",
    "unbounded-queue-in-serving", "unbounded-decode-loop",
    "unaccounted-buffer-in-stage",
    "host-augment-in-hot-path", "unsupervised-thread-in-fleet",
    "bare-except", "swallowed-exception", "raw-lock-in-threaded-module",
    "blocking-under-lock", "lock-order", "untraced-terminal-verdict",
    "syntax",
})


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _inline_allows(source: str) -> Dict[int, Set[str]]:
    allows: Dict[int, Set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _ALLOW_RE.search(text)
        if m:
            allows[i] = {r.strip() for r in m.group(1).split(",")}
    return allows


def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _qualifier(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id
    return None


def _is_literal(node: ast.AST) -> bool:
    return isinstance(node, (ast.Constant, ast.JoinedStr))


def _contains_host_pull(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) and _call_name(n) == "host_pull"
               for n in ast.walk(node))


def _has_dtype_arg(call: ast.Call, positional_slot: int) -> bool:
    if any(kw.arg == "dtype" for kw in call.keywords):
        return True
    return len(call.args) > positional_slot


# ---------------------------------------------------------------------------
# per-file rules
# ---------------------------------------------------------------------------

def _rule_host_sync(path: str, rel: str, tree: ast.AST) -> List[Finding]:
    if not (rel.endswith("engine.py") or
            any(s in rel for s in (os.path.join("optim", ""),
                                   os.path.join("parallel", "")))):
        return []
    out: List[Finding] = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.hot = 0

        def visit_FunctionDef(self, node):
            is_hot = node.name in HOT_FUNCS
            self.hot += is_hot
            self.generic_visit(node)
            self.hot -= is_hot

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            if self.hot:
                name = _call_name(node)
                qual = _qualifier(node)
                flagged = None
                if (isinstance(node.func, ast.Name) and
                        name in SYNC_BUILTINS and node.args and
                        not _is_literal(node.args[0])):
                    flagged = f"{name}(...)"
                elif qual in ("np", "numpy", "onp") and name in SYNC_NP:
                    flagged = f"{qual}.{name}(...)"
                elif (isinstance(node.func, ast.Attribute) and
                        name in SYNC_METHODS and not node.args):
                    flagged = f".{name}()"
                if flagged and not _contains_host_pull(node):
                    out.append(Finding(
                        rel, node.lineno, "host-sync-in-hot-path",
                        f"{flagged} in hot-loop function forces an implicit "
                        "device→host sync — batch it through "
                        "bigdl_tpu.analysis.host_pull"))
            self.generic_visit(node)

    V().visit(tree)
    return out


def _rule_raw_clock(path: str, rel: str, tree: ast.AST) -> List[Finding]:
    """Raw ``time.*`` reads in hot-loop functions: the telemetry clock
    is the one timer (the telemetry package itself is the clock's home
    and is exempt)."""
    if TELEMETRY_SCOPE in rel:
        return []
    out: List[Finding] = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.hot = 0

        def visit_FunctionDef(self, node):
            is_hot = node.name in HOT_FUNCS
            self.hot += is_hot
            self.generic_visit(node)
            self.hot -= is_hot

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            if (self.hot and _qualifier(node) == "time" and
                    _call_name(node) in RAW_CLOCKS):
                out.append(Finding(
                    rel, node.lineno, "raw-clock-in-hot-path",
                    f"time.{_call_name(node)}() in a hot-loop function — "
                    "measure with bigdl_tpu.telemetry.clock_ns (or a "
                    "telemetry.span) so every hot-path duration shares "
                    "one monotonic timeline"))
            self.generic_visit(node)

    V().visit(tree)
    return out


def _rule_signal_handler(path: str, rel: str, tree: ast.AST) -> List[Finding]:
    """``signal.*`` management calls inside per-iteration functions:
    handler (de)installation is run-scoped work
    (``elastic.PreemptionHandler``), never loop work."""
    out: List[Finding] = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.hot = 0

        def visit_FunctionDef(self, node):
            is_hot = node.name in SIGNAL_HOT_FUNCS
            self.hot += is_hot
            self.generic_visit(node)
            self.hot -= is_hot

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            if (self.hot and _qualifier(node) == "signal" and
                    _call_name(node) in SIGNAL_CALLS):
                out.append(Finding(
                    rel, node.lineno, "signal-handler-in-hot-path",
                    f"signal.{_call_name(node)}() in a hot-loop function "
                    "— install/restore handlers at run scope "
                    "(bigdl_tpu.utils.elastic.PreemptionHandler), not "
                    "per iteration"))
            self.generic_visit(node)

    V().visit(tree)
    return out


def _rule_dtype_drop(path: str, rel: str, tree: ast.AST) -> List[Finding]:
    if NN_SCOPE not in rel:
        return []
    out: List[Finding] = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.fwd = 0

        def visit_FunctionDef(self, node):
            is_fwd = node.name in FORWARD_FUNCS
            self.fwd += is_fwd
            self.generic_visit(node)
            self.fwd -= is_fwd

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            name = _call_name(node)
            if (self.fwd and _qualifier(node) == "jnp" and
                    name in DTYPE_DROP_FACTORIES and
                    not _has_dtype_arg(node, 1)):
                out.append(Finding(
                    rel, node.lineno, "jnp-dtype-drop",
                    f"jnp.{name} without dtype in a forward path defaults "
                    "to float32 and silently promotes a reduced-precision "
                    "forward — pass dtype=<input>.dtype"))
            self.generic_visit(node)

    V().visit(tree)
    return out


def _rule_untracked_jit(path: str, rel: str, tree: ast.AST) -> List[Finding]:
    """``jax.jit`` entry points (calls and decorators), ``.lower(...)``
    with arguments, and argument-less ``.compile()`` outside the
    registered cache wrapper file: every fused-step compilation must go
    through ``compile_cache.tracked_jit`` so it is cached, warmed ahead
    of step 1, and watchdog-supervised."""
    if any(rel.endswith(t) for t in TRACKED_JIT_FILES):
        return []
    out: List[Finding] = []

    def _flag(lineno: int, what: str) -> None:
        out.append(Finding(
            rel, lineno, "untracked-jit",
            f"{what} outside the registered cache wrapper "
            "(utils/compile_cache.py) compiles with no persistent "
            "cache, no AOT warmup, and no compile watchdog — route "
            "fused steps through compile_cache.tracked_jit"))

    #: decorator Call nodes already flagged via decorator_list — ast.walk
    #: revisits them as plain calls, which must not double-report
    flagged_decorators: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = (target.attr if isinstance(target, ast.Attribute)
                        else target.id if isinstance(target, ast.Name)
                        else None)
                if name in JIT_NAMES:
                    _flag(dec.lineno, f"@{name} decorator")
                    if isinstance(dec, ast.Call):
                        flagged_decorators.add(id(dec))
            continue
        if not isinstance(node, ast.Call) or id(node) in flagged_decorators:
            continue
        name = _call_name(node)
        qual = _qualifier(node)
        if name in JIT_NAMES and (qual == "jax" or
                                  isinstance(node.func, ast.Name)):
            _flag(node.lineno, f"{qual + '.' if qual else ''}{name}(...)")
        elif (isinstance(node.func, ast.Attribute) and name == "lower" and
                node.args):
            # str.lower() takes no arguments — only the AOT lowering
            # protocol passes the step args here
            _flag(node.lineno, ".lower(<args>)")
        elif (isinstance(node.func, ast.Attribute) and name == "compile"
                and not node.args and not node.keywords):
            # re.compile(pattern) always has arguments; an argument-less
            # .compile() is the Lowered -> Compiled AOT step
            _flag(node.lineno, ".compile()")
    return out


def _rule_undeclared_collective(path: str, rel: str,
                                tree: ast.AST) -> List[Finding]:
    """Raw ``lax`` collectives in trainer step-constructor files: the
    HLO auditor checks the LOWERED program against the step's declared
    contract; this rule keeps the SOURCE reconcilable with it — a
    collective that doesn't go through ``parallel/all_reduce.py``'s
    helpers is invisible to the contract declaration next to them."""
    if not any(rel.endswith(t) for t in TRAINER_STEP_FILES):
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name not in COLLECTIVE_CALLS:
            continue
        f = node.func
        # lax.psum(...), jax.lax.psum(...), or a bare psum(...) import —
        # the helper module's own wrappers are out of scope by file
        if isinstance(f, ast.Attribute):
            q = f.value
            lax_qual = ((isinstance(q, ast.Name) and q.id == "lax") or
                        (isinstance(q, ast.Attribute) and q.attr == "lax"))
            if not lax_qual:
                continue
        out.append(Finding(
            rel, node.lineno, "undeclared-collective",
            f"raw {name}(...) in a trainer step body — route it through "
            "the declared-contract helpers in parallel/all_reduce.py "
            "(axis_sum/axis_mean/axis_min/ring_permute/pmean_floats or "
            "AllReduceParameter) so the step's program contract stays "
            "in sync with the source"))
    return out


def _rule_unguarded_io(path: str, rel: str, tree: ast.AST) -> List[Finding]:
    """Raw ``open``-family calls in the ingest stage-thread file: stage
    threads surface errors at the consumer, so a naked read that blips
    kills the run instead of retrying — route through ``utils.file_io``
    or ``dataset.seqfile``."""
    if not any(rel.endswith(t) for t in STAGE_THREAD_FILES):
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        qual = _qualifier(node)
        raw = ((isinstance(node.func, ast.Name) and name == "open") or
               (qual in RAW_IO_QUALIFIERS and name == "open"))
        if raw:
            out.append(Finding(
                rel, node.lineno, "unguarded-io-in-stage-thread",
                f"raw {qual + '.' if qual else ''}open(...) in ingest "
                "stage-thread code — a transient storage blip here "
                "aborts the training run; route the read through "
                "utils.file_io (capped-backoff retry + chaos choke "
                "point) or dataset.seqfile (corrupt-record taxonomy)"))
    return out


def _rule_unbounded_queue(path: str, rel: str, tree: ast.AST) -> List[Finding]:
    """Unbounded ``queue.Queue()``/``deque()`` construction on the
    serving path: every ring behind the admission controller must carry
    an explicit bound, or overload becomes silent memory growth."""
    if not (SERVING_SCOPE in rel or rel.endswith("engine.py")):
        return []
    out: List[Finding] = []

    def _flag(node: ast.Call, what: str, fix: str) -> None:
        out.append(Finding(
            rel, node.lineno, "unbounded-queue-in-serving",
            f"{what} without a bound on the serving path — overload must "
            "be rejected at admission, not absorbed into an unbounded "
            f"ring; {fix}"))

    def _kw(node: ast.Call, name: str) -> Optional[ast.expr]:
        for kw in node.keywords:
            if kw.arg == name:
                return kw.value
        return None

    def _unbounding(value: Optional[ast.expr]) -> bool:
        """True when the bound expression is missing or explicitly
        0/None (both mean 'infinite' to Queue/deque)."""
        if value is None:
            return True
        return (isinstance(value, ast.Constant) and
                value.value in (0, None))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        qual = _qualifier(node)
        if name in QUEUE_CTORS and qual in ("queue", None):
            bound = node.args[0] if node.args else _kw(node, "maxsize")
            if _unbounding(bound):
                _flag(node, f"{name}()", "pass maxsize=<bound>")
        elif name == "SimpleQueue" and qual in ("queue", None):
            _flag(node, "SimpleQueue()",
                  "it cannot be bounded — use Queue(maxsize=<bound>)")
        elif name == "deque" and qual in ("collections", None):
            bound = (node.args[1] if len(node.args) > 1
                     else _kw(node, "maxlen"))
            if _unbounding(bound):
                _flag(node, "deque()", "pass maxlen=<bound>")
    return out


#: loop-test identifiers that count as a bound on a decode-path while
_DECODE_BOUND_RE = re.compile(
    r"max|deadline|remaining|budget|bound|stop|drain|terminal", re.I)
LM_SERVING_FILE = os.path.join("serving", "lm.py")


def _rule_unbounded_decode(path: str, rel: str,
                           tree: ast.AST) -> List[Finding]:
    """``while`` loops in the LM serving file must be visibly bounded:
    the test references a max/deadline/budget/terminal-style name, or
    the loop is rewritten as a bounded ``for``.  One unbounded decode
    loop wedges a slot (and its KV blocks) forever."""
    if not rel.endswith(LM_SERVING_FILE):
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.While):
            continue
        names = [n.id for n in ast.walk(node.test)
                 if isinstance(n, ast.Name)]
        names += [n.attr for n in ast.walk(node.test)
                  if isinstance(n, ast.Attribute)]
        bounded = (not isinstance(node.test, ast.Constant) and
                   any(_DECODE_BOUND_RE.search(n) for n in names))
        if not bounded:
            out.append(Finding(
                rel, node.lineno, "unbounded-decode-loop",
                "while loop on the decode path with no visible "
                "max-steps/deadline/terminal bound in its test — a "
                "sequence that never finishes would wedge its slot and "
                "KV blocks forever; use a bounded for, or test a "
                "budget/deadline/terminal condition"))
    return out


def _rule_unaccounted_buffer(path: str, rel: str,
                             tree: ast.AST) -> List[Finding]:
    """Batch-scale host allocations in stage/serving files whose scope
    never touches the resource governor: the host-memory budget can only
    hold if every buffer these paths pin is charged to an account."""
    if not (any(rel.endswith(t) for t in ACCOUNTED_BUFFER_FILES) or
            SERVING_SCOPE in rel):
        return []
    out: List[Finding] = []

    def _scale_sized(call: ast.Call) -> bool:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for n in ast.walk(arg):
                if isinstance(n, ast.Name) and _BUFFER_SCALE.search(n.id):
                    return True
                if (isinstance(n, ast.Attribute) and
                        _BUFFER_SCALE.search(n.attr)):
                    return True
        return False

    def _accounted(scope: ast.AST) -> bool:
        return any(isinstance(n, ast.Call) and
                   _call_name(n) in ACCOUNTING_CALLS
                   for n in ast.walk(scope))

    class V(ast.NodeVisitor):
        def __init__(self):
            self.scopes: List[ast.AST] = [tree]

        def visit_FunctionDef(self, node):
            self.scopes.append(node)
            self.generic_visit(node)
            self.scopes.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            name = _call_name(node)
            qual = _qualifier(node)
            is_buf = ((qual in ("np", "numpy") and
                       name in BUFFER_CTORS_NP) or
                      (isinstance(node.func, ast.Name) and
                       name == "bytearray" and node.args))
            if (is_buf and _scale_sized(node) and
                    not _accounted(self.scopes[-1])):
                out.append(Finding(
                    rel, node.lineno, "unaccounted-buffer-in-stage",
                    f"batch-scale {qual + '.' if qual else ''}{name}(...) "
                    "in a stage/serving file with no resource-governor "
                    "accounting in scope — charge it to a "
                    "bigdl_tpu.resources.GOVERNOR account (account().add "
                    "/ item_nbytes / check_item) so Resources/host_bytes "
                    "and the host-memory budget see it"))
            self.generic_visit(node)

    V().visit(tree)
    return out


def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    body = [n for n in handler.body
            if not (isinstance(n, ast.Expr) and
                    isinstance(n.value, ast.Constant))]   # docstring-ish
    return all(isinstance(n, (ast.Pass, ast.Continue)) for n in body)


def _rule_host_augment(path: str, rel: str, tree: ast.AST) -> List[Finding]:
    """Per-pixel augmentation calls in dataset hot-path modules: the
    real-data path ships raw uint8 frames and augments on device, so a
    cv2/numpy crop/flip/normalize call drifting into ``dataset/``
    outside the declared host-fallback modules re-pins the decode pool
    as the bottleneck — silently, which is why this is a lint rule and
    not a code-review note."""
    if DATASET_SCOPE not in rel:
        return []
    if any(rel.endswith(t) for t in HOST_AUGMENT_FALLBACK_FILES):
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        qual = _qualifier(node)
        flagged = None
        if qual == "cv2" and name in HOST_AUGMENT_CV2:
            flagged = f"cv2.{name}(...)"
        elif qual in ("np", "numpy") and name in HOST_AUGMENT_NP:
            flagged = f"{qual}.{name}(...)"
        elif (isinstance(node.func, ast.Attribute) and
                name in HOST_AUGMENT_METHODS):
            flagged = f".{name}(...)"
        if flagged:
            out.append(Finding(
                rel, node.lineno, "host-augment-in-hot-path",
                f"{flagged} is per-pixel host augmentation on the "
                "ingest hot path — run it on device (nn.DeviceAugment /"
                " dataset/device_augment.py) or move the code into a "
                "declared host-fallback module (dataset/image.py, "
                "dataset/mt_batch.py)"))
    return out


def _rule_fleet_thread(path: str, rel: str, tree: ast.AST) -> List[Finding]:
    """Raw ``threading.Thread`` construction in the fleet control plane:
    every fleet thread must come from ``FleetSupervisor.spawn`` (the
    registered, drainable, enumerable construction site).  A thread the
    supervisor never saw cannot be joined at fleet stop and invisibly
    weakens every chaos-accounting claim the fleet makes."""
    if FLEET_SCOPE not in rel:
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _call_name(node) != "Thread":
            continue
        if _qualifier(node) not in ("threading", None):
            continue
        out.append(Finding(
            rel, node.lineno, "unsupervised-thread-in-fleet",
            "raw threading.Thread in the fleet control plane — every "
            "fleet thread must be spawned through FleetSupervisor.spawn "
            "so fleet stop can drain it and diagnostics can enumerate "
            "it"))
    return out


def _rule_untraced_verdict(path: str, rel: str,
                           tree: ast.AST) -> List[Finding]:
    """Terminal serving errors and raw ``._finish()`` transitions in the
    serving/fleet packages must flow through the verdict choke points —
    the functions whose callers (or bodies) stamp
    ``request_trace.verdict`` and the incident ring.  A terminal error
    raised anywhere else is a request that dies without its trace ever
    saying why."""
    if not (SERVING_SCOPE in rel or FLEET_SCOPE in rel):
        return []
    out: List[Finding] = []

    def _choke(chokes: Set[Tuple[str, str]], fn: Optional[str]) -> bool:
        return any(rel.endswith(suffix) and fn == name
                   for suffix, name in chokes)

    def _visit(node: ast.AST, fn: Optional[str],
               terminal_names: Set[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = node.name
            terminal_names = set()
        if isinstance(node, ast.Assign):
            v = node.value
            if (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                    and v.func.id in TERMINAL_ERRORS):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        terminal_names.add(t.id)
        if isinstance(node, ast.Raise) and node.exc is not None:
            e = node.exc
            cls = None
            if (isinstance(e, ast.Call) and isinstance(e.func, ast.Name)
                    and e.func.id in TERMINAL_ERRORS):
                cls = e.func.id
            elif isinstance(e, ast.Name) and e.id in terminal_names:
                cls = e.id
            if cls is not None and not _choke(VERDICT_RAISE_CHOKES, fn):
                out.append(Finding(
                    rel, node.lineno, "untraced-terminal-verdict",
                    f"terminal serving error {cls} raised outside the "
                    "verdict choke points — the request trace never "
                    "records why this request died; raise it from a "
                    "validation choke (_validate/_decode/allocate) or "
                    "mint it through a _reject_locked-style helper that "
                    "stamps the verdict first"))
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_finish"
                and not _choke(VERDICT_FINISH_CHOKES, fn)):
            out.append(Finding(
                rel, node.lineno, "untraced-terminal-verdict",
                "raw terminal transition ._finish() outside the "
                "accounting chokes (_account/_finish_stream/abandon) — "
                "bypasses request_trace.verdict, the incident ring and "
                "the latency exemplar; finish through the accounting "
                "choke instead"))
        for child in ast.iter_child_nodes(node):
            _visit(child, fn, terminal_names)

    _visit(tree, None, set())
    return out


def _rule_raw_lock(path: str, rel: str, tree: ast.AST) -> List[Finding]:
    """Direct ``threading.Lock()``/``RLock()``/``Condition()`` construction
    anywhere in the package: every lock must come from
    ``analysis.make_lock``/``make_rlock``/``make_condition`` so the runtime
    lock witness sees a stable name for it.  A raw lock is invisible to the
    acquisition-order graph — a deadlock through it is a deadlock the
    witness can never report.  ``analysis/`` and ``telemetry/`` are exempt
    by design (the witness's own bookkeeping locks, and the telemetry it
    reports through, must not feed back into the witness)."""
    if any(d in rel for d in RAW_LOCK_EXEMPT_DIRS):
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name not in LOCK_CTORS:
            continue
        if _qualifier(node) != "threading":
            continue
        out.append(Finding(
            rel, node.lineno, "raw-lock-in-threaded-module",
            f"raw threading.{name}() — route it through analysis.make_"
            f"{name.lower()}(name) so the runtime lock witness can track "
            "its acquisition order"))
    return out


def _rule_exceptions(path: str, rel: str, tree: ast.AST) -> List[Finding]:
    out: List[Finding] = []
    threaded = any(rel.endswith(t) for t in THREADED_FILES)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append(Finding(
                rel, node.lineno, "bare-except",
                "bare 'except:' swallows KeyboardInterrupt/SystemExit — "
                "name the exception class"))
            continue
        if not threaded:
            continue
        t = node.type
        broad = (isinstance(t, ast.Name) and
                 t.id in ("Exception", "BaseException"))
        if broad and _handler_swallows(node):
            out.append(Finding(
                rel, node.lineno, "swallowed-exception",
                f"'except {t.id}: pass/continue' in threaded pipeline code "
                "eats worker failures silently — narrow the class "
                "(queue.Full/queue.Empty) or surface the error"))
    return out


# -- lock rules (cross-file graph) ------------------------------------------

_LOCK_HINT = re.compile(r"(_lock|_LOCK|lock)$")


def _lock_name(node: ast.AST) -> Optional[str]:
    """Identity of a lock object by its attribute/global name:
    ``self._lock`` -> ``<Class>._lock`` is not resolvable statically, so
    identity is the dotted tail (``_lock``, ``_NAME_LOCK``, ...)."""
    if isinstance(node, ast.Attribute) and _LOCK_HINT.search(node.attr):
        base = node.value
        base_name = base.id if isinstance(base, ast.Name) else "?"
        return f"{base_name}.{node.attr}"
    if isinstance(node, ast.Name) and _LOCK_HINT.search(node.id):
        return node.id
    return None


class _LockVisitor(ast.NodeVisitor):
    """Collect (outer, inner) lock-acquisition pairs and blocking calls
    made while a lock is held."""

    def __init__(self, rel: str):
        self.rel = rel
        self.held: List[Tuple[str, int]] = []
        self.edges: List[Tuple[str, str, str, int]] = []   # out, in, file, line
        self.blocking: List[Finding] = []

    def visit_With(self, node):
        acquired = []
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                expr = expr.func if isinstance(expr.func, (ast.Attribute,
                                                           ast.Name)) else expr
            name = _lock_name(expr)
            if name:
                for outer, _ in self.held:
                    self.edges.append((outer, name, self.rel, node.lineno))
                self.held.append((name, node.lineno))
                acquired.append(name)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    def visit_Call(self, node):
        if self.held:
            name = _call_name(node)
            if (isinstance(node.func, ast.Attribute) and
                    name in BLOCKING_METHODS and
                    _lock_name(node.func) is None and
                    self._blocks(node, name)):
                outer = self.held[-1][0]
                self.blocking.append(Finding(
                    self.rel, node.lineno, "blocking-under-lock",
                    f".{name}(...) called while holding {outer} — a "
                    "blocked ring handoff under a stage lock deadlocks "
                    "the pipeline"))
        self.generic_visit(node)

    @staticmethod
    def _blocks(node: ast.Call, name: str) -> bool:
        """put/get only block on queue/ring receivers (or with an explicit
        blocking timeout); join/wait/sleep/acquire always do.  The
        explicitly NON-blocking forms — block=False, timeout=0 — are the
        safe handoff under a lock and never flag."""
        if name not in ("put", "get"):
            return True
        for kw in node.keywords:
            if (kw.arg == "block" and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False):
                return False
            if (kw.arg == "timeout" and isinstance(kw.value, ast.Constant)
                    and kw.value.value == 0):
                return False
        if any(kw.arg in ("timeout", "block") for kw in node.keywords):
            return True
        recv = node.func.value
        recv_name = (recv.attr if isinstance(recv, ast.Attribute)
                     else recv.id if isinstance(recv, ast.Name) else "")
        return bool(_QUEUEISH.search(recv_name))


def _find_lock_cycles(edges) -> List[Finding]:
    graph: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for outer, inner, rel, line in edges:
        if outer == inner:
            continue
        graph.setdefault(outer, set()).add(inner)
        sites.setdefault((outer, inner), (rel, line))
    out: List[Finding] = []
    seen_pairs = set()
    for a in graph:
        for b in graph[a]:
            if a in graph.get(b, ()) and (b, a) not in seen_pairs:
                seen_pairs.add((a, b))
                rel1, l1 = sites[(a, b)]
                rel2, l2 = sites[(b, a)]
                out.append(Finding(
                    rel1, l1, "lock-order",
                    f"lock order cycle: {a} -> {b} here but {b} -> {a} at "
                    f"{rel2}:{l2} — two threads can deadlock on the pair"))
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _package_base(path: str) -> str:
    """Anchor for repo-relative paths: the parent of the TOPMOST package
    containing ``path``.  This makes ``Finding.path`` (and therefore the
    path-scoped rules and allowlist keys) invocation-independent —
    linting ``bigdl_tpu``, ``bigdl_tpu/optim``, or a single
    ``optim/metrics.py`` all report ``bigdl_tpu/optim/metrics.py``."""
    anchor = os.path.abspath(path)
    if os.path.isfile(anchor):
        anchor = os.path.dirname(anchor)
    while os.path.exists(os.path.join(anchor, "__init__.py")):
        anchor = os.path.dirname(anchor)
    return anchor


def _iter_sources(targets: Sequence[str]) -> Iterable[Tuple[str, str]]:
    """(abs path, package-relative path) for every .py under the targets.
    A bare package name resolves relative to this file's grandparent (the
    repo layout), then the cwd."""
    for t in targets:
        root = t
        if not os.path.exists(root):
            here = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            cand = os.path.join(here, t)
            root = cand if os.path.exists(cand) else t
        base = _package_base(root)
        if os.path.isfile(root):
            yield root, os.path.relpath(os.path.abspath(root), base)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    p = os.path.join(dirpath, fn)
                    yield p, os.path.relpath(os.path.abspath(p), base)


def load_allowlist(path: Optional[str]) -> Set[str]:
    """``<relpath>:<rule>`` entries; '#' comments and blanks ignored."""
    if not path or not os.path.exists(path):
        return set()
    out = set()
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                out.add(line)
    return out


def lint_paths(targets: Sequence[str],
               allowlist: Optional[Set[str]] = None) -> List[Finding]:
    allowlist = allowlist or set()
    findings: List[Finding] = []
    lock_edges = []
    for path, rel in _iter_sources(targets):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 0, "syntax",
                                    f"unparseable: {e.msg}"))
            continue
        allows = _inline_allows(source)
        file_findings = (_rule_host_sync(path, rel, tree) +
                         _rule_raw_clock(path, rel, tree) +
                         _rule_signal_handler(path, rel, tree) +
                         _rule_dtype_drop(path, rel, tree) +
                         _rule_untracked_jit(path, rel, tree) +
                         _rule_undeclared_collective(path, rel, tree) +
                         _rule_unguarded_io(path, rel, tree) +
                         _rule_unbounded_queue(path, rel, tree) +
                         _rule_unbounded_decode(path, rel, tree) +
                         _rule_unaccounted_buffer(path, rel, tree) +
                         _rule_host_augment(path, rel, tree) +
                         _rule_fleet_thread(path, rel, tree) +
                         _rule_untraced_verdict(path, rel, tree) +
                         _rule_raw_lock(path, rel, tree) +
                         _rule_exceptions(path, rel, tree))
        if any(rel.endswith(t) for t in THREADED_FILES):
            lv = _LockVisitor(rel)
            lv.visit(tree)
            lock_edges.extend(lv.edges)
            file_findings.extend(lv.blocking)
        for f in file_findings:
            if f.rule in allows.get(f.line, ()):
                continue
            if f"{f.path}:{f.rule}" in allowlist:
                continue
            findings.append(f)
    for f in _find_lock_cycles(lock_edges):
        if f"{f.path}:{f.rule}" not in allowlist:
            findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "lint_allowlist.txt")


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.analysis.lint",
        description="static lint for host-sync/dtype/exception/lock rules")
    ap.add_argument("targets", nargs="+",
                    help="package directories or .py files")
    ap.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                    help="grandfathered '<relpath>:<rule>' entries "
                         "(default: the in-repo allowlist, kept empty)")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="NAME",
                    help="report only this rule (repeatable); an unknown "
                         "name is an error, not an empty report")
    args = ap.parse_args(argv)
    if args.rule:
        unknown = sorted(set(args.rule) - KNOWN_RULES)
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}\n"
                  f"known rules: {', '.join(sorted(KNOWN_RULES))}",
                  file=sys.stderr)
            return 2
    findings = lint_paths(args.targets, load_allowlist(args.allowlist))
    if args.rule:
        findings = [f for f in findings if f.rule in set(args.rule)]
    for f in findings:
        print(f)
    if findings:
        print(f"\n{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
