"""TFRecord-framed event file writing.

Reference equivalents: ``visualization/tensorboard/RecordWriter.scala:30-57``
(length + masked-CRC32C framing), ``EventWriter.scala`` (async queue draining
to an ``events.out.tfevents.<ts>.<host>`` file), ``FileWriter.scala:30``
(user-facing handle).  Files are readable by stock TensorBoard.
"""

from __future__ import annotations

import os
import queue
import socket
import struct
import threading
import time
from typing import Optional

from bigdl_tpu.visualization.crc32c import masked_crc32c
from bigdl_tpu.visualization import proto


def write_record(f, data: bytes) -> None:
    """One TFRecord frame: u64 length, u32 masked-crc(length), payload,
    u32 masked-crc(payload) (reference RecordWriter.scala:38-44)."""
    header = struct.pack("<Q", len(data))
    f.write(header)
    f.write(struct.pack("<I", masked_crc32c(header)))
    f.write(data)
    f.write(struct.pack("<I", masked_crc32c(data)))


def read_records(path: str):
    """Iterate raw event payloads from an events file, checking CRCs
    (readback support, reference TrainSummary.readScalar).  A short read
    anywhere mid-record means an in-progress append — treated as
    end-of-stream, not corruption."""
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return
            (length,) = struct.unpack("<Q", header)
            hcrc_raw = f.read(4)
            if len(hcrc_raw) < 4:
                return
            if struct.unpack("<I", hcrc_raw)[0] != masked_crc32c(header):
                raise IOError(f"corrupt record header in {path}")
            data = f.read(length)
            if len(data) < length:
                return
            dcrc_raw = f.read(4)
            if len(dcrc_raw) < 4:
                return
            if struct.unpack("<I", dcrc_raw)[0] != masked_crc32c(data):
                raise IOError(f"corrupt record payload in {path}")
            yield data


class EventWriter:
    """Background-thread writer draining an event queue to one events file
    (reference ``EventWriter.scala``: async append, periodic flush)."""

    def __init__(self, log_dir: str, flush_secs: float = 2.0):
        os.makedirs(log_dir, exist_ok=True)
        # the pid suffix (TF2's own convention) keeps two same-host
        # processes created in the same second from appending to one file
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}.{os.getpid()}")
        self.path = os.path.join(log_dir, fname)
        self._f = open(self.path, "ab")
        self._q: "queue.Queue[Optional[bytes]]" = queue.Queue()
        self._flush_secs = flush_secs
        self._error: Optional[Exception] = None
        # TensorBoard requires this version marker as the first event
        self.add_event(proto.encode_event(file_version="brain.Event:2"))
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def add_event(self, event: bytes) -> None:
        self._q.put(event)

    def _run(self) -> None:
        last_flush = time.time()
        while True:
            try:
                ev = self._q.get(timeout=self._flush_secs)
            except queue.Empty:
                self._f.flush()
                last_flush = time.time()
                continue
            try:
                if ev is None:
                    self._f.flush()
                    return
                try:
                    write_record(self._f, ev)
                except Exception as e:  # keep draining so flush() can't hang
                    if self._error is None:
                        self._error = e
                        import logging
                        logging.getLogger("bigdl_tpu").error(
                            "event write failed, dropping further summaries: "
                            "%s", e)
            finally:
                self._q.task_done()
            if time.time() - last_flush >= self._flush_secs:
                self._f.flush()
                last_flush = time.time()

    def flush(self) -> None:
        """Block until every queued event is on disk."""
        self._q.join()
        self._f.flush()

    def close(self) -> None:
        self._q.join()
        self._q.put(None)
        self._thread.join(timeout=10)
        self._f.close()


class FileWriter:
    """User-facing writer (reference ``FileWriter.scala:30``).

    The events file is created lazily on the first write: under
    multi-host training every process constructs the summary objects
    (the SPMD script runs everywhere) but only the writer process emits
    events (``optim.optimizer.is_writer_process``) — constructing a
    FileWriter must therefore not leave an empty events file behind on
    the N-1 silent processes."""

    def __init__(self, log_dir: str, flush_secs: float = 2.0):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._flush_secs = flush_secs
        self._writer: Optional[EventWriter] = None

    def _ensure_writer(self) -> EventWriter:
        if self._writer is None:
            self._writer = EventWriter(self.log_dir, self._flush_secs)
        return self._writer

    def add_summary(self, summary: bytes, global_step: int) -> "FileWriter":
        self._ensure_writer().add_event(
            proto.encode_event(step=global_step, summary=summary))
        return self

    def add_event(self, event: bytes) -> "FileWriter":
        self._ensure_writer().add_event(event)
        return self

    def flush(self) -> "FileWriter":
        if self._writer is not None:
            self._writer.flush()
        return self

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
