"""TrainSummary / ValidationSummary: the user-facing TensorBoard API.

Reference equivalents: ``visualization/Summary.scala:32`` (base: FileWriter
ownership, scalar/histogram builders with exponential buckets),
``TrainSummary.scala:32`` (auto-logged Loss/Throughput/LearningRate +
trigger-gated "Parameters" histograms), ``ValidationSummary.scala``.

The optimizer's driver loop calls ``add_scalar`` each iteration (Loss,
Throughput, LearningRate) and ``save_parameters`` when the "Parameters"
trigger fires — the same call sites as the reference
(``optim/DistriOptimizer.scala:356-374,426-456``).
"""

from __future__ import annotations

import math
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.visualization import proto
from bigdl_tpu.visualization.file_writer import FileWriter, read_records


def _exponential_buckets() -> List[float]:
    """The reference's bucket edges: ±1e-12 · 1.1^k plus sentinels
    (``visualization/Summary.scala:108-126``)."""
    pos = []
    v = 1e-12
    while v < 1e20:
        pos.append(v)
        v *= 1.1
    return [-b for b in reversed(pos)] + [0.0] + pos


_BUCKETS = None


def _bucket_edges() -> List[float]:
    global _BUCKETS
    if _BUCKETS is None:
        _BUCKETS = _exponential_buckets()
    return _BUCKETS


def scalar_summary(tag: str, value: float) -> bytes:
    """(reference ``Summary.scalar:95``)."""
    return proto.encode_summary(
        [proto.encode_summary_value(tag, simple_value=float(value))])


def histogram_summary(tag: str, values: np.ndarray) -> bytes:
    """(reference ``Summary.histogram:108``)."""
    values = np.asarray(values, dtype=np.float64).ravel()
    edges = np.asarray(_bucket_edges())
    counts, _ = np.histogram(values, bins=np.concatenate(
        ([-np.inf], edges, [np.inf])))
    # collapse the trailing overflow bin into the last edge bucket
    counts = counts.astype(np.float64)
    counts[-2] += counts[-1]
    counts = counts[:-1]
    nz = np.nonzero(counts)[0]
    if nz.size:
        lo, hi = nz[0], nz[-1] + 1
        limits, cts = edges[lo:hi], counts[lo:hi]
    else:
        limits, cts = edges[:1], counts[:1]
    histo = proto.encode_histogram(
        float(values.min()) if values.size else 0.0,
        float(values.max()) if values.size else 0.0,
        float(values.size), float(values.sum()),
        float((values ** 2).sum()), limits.tolist(), cts.tolist())
    return proto.encode_summary([proto.encode_summary_value(tag, histo=histo)])


class Summary:
    """Base class holding a FileWriter (reference ``Summary.scala:32``)."""

    def __init__(self, log_dir: str, app_name: str, sub_dir: str):
        self.log_dir = os.path.join(log_dir, app_name, sub_dir)
        self._writer = FileWriter(self.log_dir)

    def add_scalar(self, tag: str, value: float, step: int) -> "Summary":
        self._writer.add_summary(scalar_summary(tag, value), step)
        return self

    def add_histogram(self, tag: str, values, step: int) -> "Summary":
        self._writer.add_summary(histogram_summary(tag, np.asarray(values)),
                                 step)
        return self

    def read_scalar(self, tag: str) -> List[Tuple[int, float]]:
        """[(step, value)] for a tag, parsed back from the event files
        (reference ``TrainSummary.readScalar``)."""
        self._writer.flush()
        out = []
        for fname in sorted(os.listdir(self.log_dir)):
            if not fname.startswith("events.out.tfevents"):
                continue
            for rec in read_records(os.path.join(self.log_dir, fname)):
                ev = proto.decode_event(rec)
                for v in ev["values"]:
                    if v["tag"] == tag and v["simple_value"] is not None:
                        out.append((int(ev["step"]), float(v["simple_value"])))
        return out

    def flush(self) -> "Summary":
        self._writer.flush()
        return self

    def close(self) -> None:
        self._writer.close()


class TrainSummary(Summary):
    """(reference ``TrainSummary.scala:32``).  Loss/Throughput/LearningRate
    are logged every iteration by the driver loop; "Parameters" histograms
    are gated by :meth:`set_summary_trigger`."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "train")
        self._triggers: Dict[str, object] = {}

    def set_summary_trigger(self, name: str, trigger) -> "TrainSummary":
        if name not in ("Loss", "Throughput", "LearningRate", "Parameters"):
            raise ValueError(f"unsupported summary name {name!r}")
        self._triggers[name] = trigger
        return self

    def get_summary_trigger(self, name: str):
        return self._triggers.get(name)

    def save_parameters_due(self, state) -> bool:
        trig = self._triggers.get("Parameters")
        return trig is not None and trig(state)

    def save_parameters(self, model, step: int) -> None:
        """Per-layer weight histograms (the reference pulls the full model
        for this — costly, hence trigger-gated;
        ``optim/DistriOptimizer.scala:426-456``).  Gradient histograms are
        deliberately absent: the fused jitted step consumes gradients
        on-device without materialising them host-side."""
        for name, params in model.get_parameters_table().items():
            for leaf_name, leaf in _named_leaves(params):
                self.add_histogram(f"{name}/{leaf_name}", np.asarray(leaf),
                                   step)


def _named_leaves(tree, prefix=""):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _named_leaves(v, f"{prefix}{k}.")
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _named_leaves(v, f"{prefix}{i}.")
    else:
        yield (prefix.rstrip(".") or "value"), tree


class ValidationSummary(Summary):
    """(reference ``ValidationSummary.scala``): one scalar per validation
    metric, written by the driver after each validation pass."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "validation")
