"""Hand-rolled protobuf wire encoding for TensorFlow Event/Summary messages.

Reference equivalent: the generated ``org.tensorflow.util.Event`` /
``org.tensorflow.framework.Summary`` Java protos consumed by
``visualization/Summary.scala:87-130``.  The rebuild needs only the tiny
subset TensorBoard reads (scalar + histogram events), so the five message
types are encoded directly on the wire format — no protobuf runtime.

Wire format: each field is ``(field_number << 3 | wire_type)`` varint + data.
wire types: 0 varint, 1 fixed64 (double), 2 length-delimited, 5 fixed32.
"""

from __future__ import annotations

import struct
import time
from typing import List, Optional, Sequence


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _double(field: int, v: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", v)


def _float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def _int64(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _bytes(field: int, v: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(v)) + v


def _string(field: int, v: str) -> bytes:
    return _bytes(field, v.encode("utf-8"))


def _packed_doubles(field: int, vs: Sequence[float]) -> bytes:
    data = b"".join(struct.pack("<d", v) for v in vs)
    return _bytes(field, data)


def encode_histogram(minv: float, maxv: float, num: float, total: float,
                     sum_squares: float, bucket_limits: Sequence[float],
                     buckets: Sequence[float]) -> bytes:
    """HistogramProto: min=1 max=2 num=3 sum=4 sum_squares=5
    bucket_limit=6(packed) bucket=7(packed)."""
    return (_double(1, minv) + _double(2, maxv) + _double(3, num) +
            _double(4, total) + _double(5, sum_squares) +
            _packed_doubles(6, bucket_limits) + _packed_doubles(7, buckets))


def encode_summary_value(tag: str, simple_value: Optional[float] = None,
                         histo: Optional[bytes] = None) -> bytes:
    """Summary.Value: tag=1, simple_value=2(float), histo=5(message)."""
    out = _string(1, tag)
    if simple_value is not None:
        out += _float(2, simple_value)
    if histo is not None:
        out += _bytes(5, histo)
    return out


def encode_summary(values: List[bytes]) -> bytes:
    """Summary: repeated value=1."""
    return b"".join(_bytes(1, v) for v in values)


def encode_event(wall_time: Optional[float] = None, step: Optional[int] = None,
                 file_version: Optional[str] = None,
                 summary: Optional[bytes] = None) -> bytes:
    """Event: wall_time=1(double), step=2(int64), file_version=3(string),
    summary=5(message)."""
    out = _double(1, time.time() if wall_time is None else wall_time)
    if step is not None:
        out += _int64(2, step)
    if file_version is not None:
        out += _string(3, file_version)
    if summary is not None:
        out += _bytes(5, summary)
    return out


# ---------------------------------------------------------------------------
# minimal decoder (test/readback support — reference TrainSummary.readScalar)
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, pos: int):
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def decode_fields(buf: bytes):
    """Yield (field_number, wire_type, value) triples from one message."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
        elif wire == 1:
            v = struct.unpack("<d", buf[pos:pos + 8])[0]
            pos += 8
        elif wire == 2:
            n, pos = _read_varint(buf, pos)
            v = buf[pos:pos + n]
            pos += n
        elif wire == 5:
            v = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, v


def decode_event(buf: bytes) -> dict:
    """Decode the Event subset written above."""
    out = {"wall_time": None, "step": 0, "file_version": None, "values": []}
    for field, wire, v in decode_fields(buf):
        if field == 1:
            out["wall_time"] = v
        elif field == 2:
            out["step"] = v
        elif field == 3:
            out["file_version"] = v.decode("utf-8")
        elif field == 5:
            for f2, _, v2 in decode_fields(v):
                if f2 == 1:  # Summary.Value
                    val = {"tag": None, "simple_value": None, "histo": None}
                    for f3, w3, v3 in decode_fields(v2):
                        if f3 == 1:
                            val["tag"] = v3.decode("utf-8")
                        elif f3 == 2:
                            val["simple_value"] = v3
                        elif f3 == 5:
                            val["histo"] = v3
                    out["values"].append(val)
    return out
