"""bigdl_tpu.visualization — TensorBoard-compatible training visualization.

Reference equivalent: ``visualization/`` (Summary/TrainSummary/
ValidationSummary over a TFRecord event writer with masked CRC32C framing,
``visualization/tensorboard/FileWriter.scala:30``, ``RecordWriter.scala:30-57``).
"""

from bigdl_tpu.visualization.crc32c import crc32c, masked_crc32c
from bigdl_tpu.visualization.file_writer import FileWriter, read_records
from bigdl_tpu.visualization.summary import (Summary, TrainSummary,
                                             ValidationSummary,
                                             scalar_summary,
                                             histogram_summary)

__all__ = ["FileWriter", "Summary", "TrainSummary", "ValidationSummary",
           "crc32c", "masked_crc32c", "read_records", "scalar_summary",
           "histogram_summary"]
