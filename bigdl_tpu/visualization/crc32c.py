"""CRC32C (Castagnoli) with TFRecord masking.

Reference equivalent: ``spark/dl/src/main/java/com/intel/analytics/bigdl/
visualization/tensorboard/netty/Crc32c.java`` (vendored netty CRC32C) and the
masking in ``visualization/tensorboard/RecordWriter.scala:30-57``.

Table-driven, polynomial 0x1EDC6F41 (reflected 0x82F63B78) — the checksum
TensorBoard requires on every TFRecord frame.
"""

from __future__ import annotations

_POLY = 0x82F63B78
_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _POLY if _c & 1 else _c >> 1
    _TABLE.append(_c)


def crc32c(data: bytes, crc: int = 0) -> int:
    crc = crc ^ 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    """TFRecord masking: rotate right by 15 and add a constant."""
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF
