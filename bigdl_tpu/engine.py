"""Engine: process-wide topology and device management.

Reference equivalent: ``utils/Engine.scala:36`` — a singleton owning engine type
(MklBlas), node/core topology, and the two CPU thread pools (``Engine.default``
coarse task pool, ``Engine.model`` intra-layer pool).

On TPU none of the thread-pool machinery survives: XLA owns intra-op
parallelism, and the "N model replicas per node sharing one weight storage"
trick (reference ``optim/DistriOptimizer.scala:516-531``) collapses into a
single larger per-chip batch under ``jit``.  What remains is topology: which
devices exist, how they are arranged into a ``jax.sharding.Mesh``, and a
single place to configure precision and engine type.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional, Sequence

import jax
import numpy as np

from bigdl_tpu import analysis, telemetry
from bigdl_tpu.resources import GOVERNOR as _resource_governor


class DispatchPipeline:
    """Bounded queue of in-flight device results with async device→host
    copies — the dispatch-pipelining idiom shared by the training driver,
    evaluator, and predictor.

    Each device round-trip (reading a loss/output) costs a full RTT when
    the chip sits behind a network tunnel; keeping ``depth - 1`` results
    in flight and starting the host copy at dispatch hides it.  ``depth``
    defaults to ``bigdl.pipeline.depth`` (1 = fully synchronous).

    ``drain(item, next_item_or_None)`` is called FIFO as results retire;
    ``next_item`` peeks the queue so callers can measure inter-dispatch
    intervals."""

    def __init__(self, drain, depth: Optional[int] = None):
        from bigdl_tpu.utils import config
        self.depth = max(1, depth if depth is not None
                         else config.get_int("bigdl.pipeline.depth", 8))
        self._drain = drain
        # bounded ring (the unbounded-queue-in-serving lint rule); the
        # pre-append drain in push() keeps len < depth == maxlen at
        # every append, so deque's eviction can never actually trigger
        # and silently drop an undrained item
        self._q = deque(maxlen=self.depth)

    def push(self, out_dev, *meta) -> None:
        if hasattr(out_dev, "copy_to_host_async"):
            out_dev.copy_to_host_async()
        # drain BEFORE append: even if some future path breaks the
        # len <= depth-1 post-condition, append happens below capacity
        # and maxlen never evicts (an invariant guard, not a policy)
        while len(self._q) >= self.depth:
            self._pop()
        self._q.append((out_dev,) + meta)
        while len(self._q) >= self.depth:
            self._pop()

    def flush(self) -> None:
        while self._q:
            self._pop()

    def abandon(self) -> int:
        """Drop every in-flight item WITHOUT draining it — the serving
        shed path: a consumer that stopped caring must not pay a
        device→host pull per result it will discard.  Outstanding async
        copies complete (or are dropped) inside the runtime; ``drain``
        is never called for them.  Returns how many items were
        abandoned."""
        n = len(self._q)
        self._q.clear()
        return n

    def _pop(self) -> None:
        item = self._q.popleft()
        self._drain(item, self._q[0] if self._q else None)


class BatchPrefetcher:
    """Background thread running ``fetch()`` ahead of the training loop.

    The fetch path ends in a host→device transfer (``device_put`` /
    ``make_array_from_process_local_data``) that costs a tunnel
    round-trip when the chip is remote (~15 ms measured) — overlapping
    it with the jitted step removes it from the critical path.  Single
    producer: the loop thread only consumes, so dataset iterators are
    never touched concurrently (an epoch reset swaps the iterator
    reference the fetch closure reads — the training stream is infinite,
    so a batch prefetched across the boundary stays valid, exactly like
    the reference's pipelined RDD fetch).

    Epoch rollovers happen ON the producer (``on_batch`` hook, wired by
    the driver): the producer alone counts records and calls
    ``reset_epoch`` at the boundary, so the dataset's iterators and
    shuffled index arrays are only ever touched from one thread AND the
    batch sequence is deterministic — independent of how far ahead the
    producer happens to be, which matters for multi-host parity (every
    process must consume the identical sequence).

    Transfer-ahead stage: with ``transfer_ahead`` > 1 (default
    ``bigdl.ingest.batchesInFlight``, 2) the fetch producer and the
    ready-wait are SPLIT across two threads so up to N host→device
    uploads are in flight at once — the fetch thread issues batch k+1's
    ``device_put`` while the transfer thread is still blocking batch k
    device-resident.  When compute ≥ transfer, the consuming step then
    never waits on the link; with ``transfer_ahead`` <= 1 the producer
    fetches and blocks serially (one upload in flight — the pre-streaming
    behaviour).  Batch ORDER is unchanged either way (both hops are FIFO
    queues) and the fetch thread remains the single producer owning epoch
    rollovers and the RNG stream.

    ``depth`` defaults to ``bigdl.prefetch.depth`` (2); 0 disables (the
    call becomes a passthrough).  Exceptions in the producer re-raise at
    the consuming call site.
    """

    def __init__(self, fetch, depth: Optional[int] = None,
                 on_batch=None, transfer_ahead: Optional[int] = None,
                 guard=None):
        import queue

        from bigdl_tpu.utils import config
        #: optional host-sync guard (bigdl_tpu.analysis) armed around the
        #: user fetch callable — the guard's hooks are thread-local, so
        #: the trainer's hot-loop arming cannot see work that runs HERE
        #: on the producer thread; arming at the call site closes that
        self._guard = guard
        self.depth = (depth if depth is not None
                      else config.get_int("bigdl.prefetch.depth", 2))
        self.transfer_ahead = (
            transfer_ahead if transfer_ahead is not None
            else config.get_int("bigdl.ingest.batchesInFlight", 2))
        self._fetch = fetch
        self._on_batch = on_batch
        # transfer-stage counters: how long the pipeline spent blocking
        # uploads device-resident vs fetching — surfaced by bench.py and
        # the driver's end-of-run metrics.  Written from the fetch AND
        # transfer producers AND the passthrough (depth 0) caller, so
        # they share a stats lock
        self._stats_lock = analysis.make_lock("engine.prefetch")
        self.fetch_ns = 0            # guarded-by: _stats_lock
        self.block_ns = 0            # guarded-by: _stats_lock
        self.batches = 0             # guarded-by: _stats_lock
        # transfer-ahead slot accounting: every batch sitting in the
        # prefetch rings (fetched but not yet consumed) charges its host
        # bytes to the governor — the read-ahead depth is exactly the
        # buffer the host-memory budget needs to see
        self._slot_acct = _resource_governor.account("prefetch_slots")
        # the producer owns epoch rollovers (reshuffles): it must continue
        # the CONSTRUCTING thread's RNG stream, so a user's set_seed on the
        # main thread keeps governing epoch 2+ shuffles whether or not
        # prefetch is enabled
        from bigdl_tpu.utils.random_generator import RandomGenerator
        self._rng = RandomGenerator.RNG()
        #: producer failure recovered by stop() after the consumer
        #: abandoned mid-stream (never raised at a call site) — the
        #: original error must survive the teardown, not vanish with
        #: the drained queues
        self.error: Optional[BaseException] = None   # guarded-by: _stats_lock
        if self.depth <= 0:
            return
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._transfer_thread = None
        if self.transfer_ahead > 1:
            # issued-but-not-yet-ready uploads queue here; capacity N-1
            # plus the one the transfer thread is blocking = N in flight
            self._issued_q: "queue.Queue" = queue.Queue(
                maxsize=self.transfer_ahead - 1)
            self._transfer_thread = threading.Thread(
                target=self._run_transfer, daemon=True,
                name="prefetch-transfer")
            self._transfer_thread.start()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="prefetch-fetch")
        self._thread.start()

    # batches at or above this size are blocked device-resident before
    # handoff; smaller ones stay async (see _block_ready)
    READY_BYTES = 4 << 20

    def _block_ready(self, batch):
        # LARGE batches are handed to the consumer DEVICE-RESIDENT:
        # dispatching a step against an in-flight bulk transfer costs ~10x
        # the step latency on the tunneled backend (measured: 1.9 s vs
        # 0.16 s for a 77 MB ResNet-50 b128 batch), so the pipeline
        # absorbs the wait, overlapped with the consumer's dispatches.
        # SMALL batches must NOT block: each block costs a full tunnel
        # round-trip (~60-150 ms), which swamps a small-model step —
        # measured 194 ms/it vs 10.6 ms/it on the LeNet perf harness —
        # while small in-flight transfers dispatch cleanly.
        leaves = jax.tree_util.tree_leaves(batch)
        total = sum(getattr(leaf, "nbytes", 0) for leaf in leaves)
        if total >= self.READY_BYTES:
            t0 = telemetry.clock_ns()
            for leaf in leaves:
                if hasattr(leaf, "block_until_ready"):
                    leaf.block_until_ready()
            t1 = telemetry.clock_ns()
            with self._stats_lock:
                self.block_ns += t1 - t0
            telemetry.add_span("prefetch/transfer", t0, t1,
                               {"bytes": total})
        return batch

    def _fetch_once(self, block: bool = True):
        t0 = telemetry.clock_ns()
        if self._guard is not None:
            with self._guard.armed():
                batch = self._fetch()
        else:
            batch = self._fetch()
        if self._on_batch is not None:
            self._on_batch(batch)
        t1 = telemetry.clock_ns()
        with self._stats_lock:
            self.fetch_ns += t1 - t0
            self.batches += 1
        telemetry.add_span("prefetch/fetch", t0, t1)
        if block:
            self._block_ready(batch)
        return batch

    def _put(self, q, item) -> bool:
        import queue as _queue
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    @staticmethod
    def _slot_nbytes(batch) -> int:
        return int(sum(int(getattr(leaf, "nbytes", 0) or 0)
                       for leaf in jax.tree_util.tree_leaves(batch)))

    def _run(self):
        from bigdl_tpu.utils.random_generator import RandomGenerator
        RandomGenerator.adopt(self._rng)
        staged = self._transfer_thread is not None
        out_q = self._issued_q if staged else self._q
        while not self._stop.is_set():
            if _resource_governor.under_pressure():
                # host-memory pressure: pause read-ahead — batches
                # already queued keep flowing to the consumer while the
                # accounted prefetch bytes drain down
                self._stop.wait(0.05)
                continue
            try:
                # staged: hand the batch on with its upload still in
                # flight — the transfer thread blocks it ready while this
                # thread fetches (and uploads) the next one
                item = (None, self._fetch_once(block=not staged))
            except BaseException as e:  # noqa: BLE001 — re-raised at call
                item = (e, None)
            if item[0] is None:
                self._slot_acct.add(self._slot_nbytes(item[1]))
            if not self._put(out_q, item):
                self._discard(item)
                return
            if item[0] is not None:
                return

    def _run_transfer(self):
        import queue as _queue
        while not self._stop.is_set():
            try:
                item = self._issued_q.get(timeout=0.1)
            except _queue.Empty:
                continue
            err, batch = item
            if err is None:
                try:
                    self._block_ready(batch)
                except BaseException as e:  # noqa: BLE001 — re-raised
                    self._slot_acct.sub(self._slot_nbytes(batch))
                    item = (e, None)
            if not self._put(self._q, item):
                self._discard(item)
                return
            if item[0] is not None:
                return

    def _stash_error(self, item) -> None:
        """A producer stopped while holding an item it could not hand
        downstream: an ERROR item dropped here would vanish — the one
        window stop()'s post-join queue drain cannot see — so park it on
        ``self.error`` directly (threads are joined before the drain
        reads it).  First error wins — atomically, since both producer
        threads and a stopping consumer can race here."""
        with self._stats_lock:
            if item[0] is not None and self.error is None:
                self.error = item[0]

    def _discard(self, item) -> None:
        """An item dropped without ever reaching the consumer: release
        its accounted slot bytes, then preserve any error it carried."""
        if item[1] is not None:
            self._slot_acct.sub(self._slot_nbytes(item[1]))
        self._stash_error(item)

    def __call__(self):
        if self.depth <= 0:
            return self._fetch_once()
        err, batch = self._q.get()
        if err is not None:
            raise err
        self._slot_acct.sub(self._slot_nbytes(batch))
        return batch

    def stop(self):
        """Stop and JOIN the producers: a retry-from-failure restart must
        not race a still-running old producer over the same dataset
        iterators.  A consumer ABANDONING mid-stream (the serving shed
        path) calls this too — after the join, any producer error still
        parked in the rings is recovered onto ``self.error`` so the
        original failure surfaces instead of being torn down with the
        queues."""
        if self.depth <= 0:
            return
        self._stop.set()
        self._thread.join(timeout=10)
        if self._transfer_thread is not None:
            self._transfer_thread.join(timeout=10)
        import queue as _queue
        for q in (self._q, getattr(self, "_issued_q", None)):
            if q is None:
                continue
            while True:
                try:
                    err, batch = q.get(block=False)
                except _queue.Empty:
                    break
                if batch is not None:
                    self._slot_acct.sub(self._slot_nbytes(batch))
                self._stash_error((err, None))


class _EngineState:
    def __init__(self):
        self.engine_type: str = "tpu"
        self.node_number: int = 1
        self.core_number: int = 1
        self.inited: bool = False
        self.seed: int = 0
        self._mesh = None
        self._lock = analysis.make_rlock("engine.state")


_STATE = _EngineState()


class Engine:
    """Static topology singleton (mirrors reference ``utils/Engine``)."""

    MESH_AXES = ("data", "model", "seq")

    @staticmethod
    def honor_virtual_devices() -> None:
        """Honor an XLA_FLAGS virtual host-device request even when a site
        hook pre-registered an accelerator backend: on this image the env
        var alone is not enough, the platform must be forced to cpu before
        jax initializes its backend.  Call early in any entry point that
        should respect ``--xla_force_host_platform_device_count``."""
        import os
        if "xla_force_host_platform_device_count" in os.environ.get(
                "XLA_FLAGS", ""):
            try:
                import jax
                jax.config.update("jax_platforms", "cpu")
            except Exception:  # lint: allow(swallowed-exception)
                # best-effort: a backend already initialized keeps
                # whatever platform it pinned
                pass

    @staticmethod
    def init(node_number: Optional[int] = None,
             core_number: Optional[int] = None,
             engine_type: Optional[str] = None) -> None:
        """Initialise topology.

        ``node_number``/``core_number`` keep the reference's vocabulary
        (reference ``utils/Engine.scala:313``): here a "node" is a host
        participating in the jax distributed runtime and a "core" is a local
        accelerator device.  Defaults are discovered from JAX.
        """
        with _STATE._lock:
            _STATE.engine_type = engine_type or os.environ.get(
                "BIGDL_ENGINE_TYPE", _default_engine_type())
            _STATE.node_number = node_number or jax.process_count()
            _STATE.core_number = core_number or jax.local_device_count()
            _STATE.inited = True

    @staticmethod
    def init_distributed(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
        """Join the multi-host jax distributed runtime (the reference's
        multi-node tier: one Spark executor per node; here one host process
        per TPU host, SURVEY §7 hard-parts note).  After this,
        ``jax.devices()`` spans all hosts and ``Engine.create_mesh`` builds
        global meshes whose collectives ride ICI within a pod and DCN
        across pods.  No-op when already initialised."""
        # idempotence via jax's own distributed state: touching the backend
        # (e.g. jax.process_count()) before initialize() would pre-initialise
        # local-only XLA and break the multi-host bring-up.  Try the public
        # is_initialized() first; fall back to the internal client handle
        # (jax.distributed exposed global_state publicly in some versions)
        is_init = getattr(jax.distributed, "is_initialized", None)
        if is_init is not None:
            if is_init():
                return
        else:
            from jax._src import distributed as _dist
            if getattr(getattr(_dist, "global_state", None),
                       "client", None) is not None:
                return
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        Engine.init()

    @staticmethod
    def node_number() -> int:
        Engine._ensure()
        return _STATE.node_number

    @staticmethod
    def core_number() -> int:
        Engine._ensure()
        return _STATE.core_number

    @staticmethod
    def engine_type() -> str:
        Engine._ensure()
        return _STATE.engine_type

    @staticmethod
    def device_count() -> int:
        return jax.device_count()

    @staticmethod
    def devices():
        return jax.devices()

    @staticmethod
    def set_seed(seed: int) -> None:
        _STATE.seed = seed

    @staticmethod
    def get_seed() -> int:
        return _STATE.seed

    # ---- mesh -----------------------------------------------------------

    @staticmethod
    def create_mesh(mesh_shape: Optional[Sequence[int]] = None,
                    axis_names: Optional[Sequence[str]] = None,
                    devices=None) -> "jax.sharding.Mesh":
        """Build a device mesh.

        Default: all devices on the ``data`` axis (pure data parallelism —
        the only parallelism the reference supports, SURVEY §2.12).  Pass
        ``mesh_shape``/``axis_names`` for dp x tp x sp meshes.
        """
        from jax.sharding import Mesh

        devs = np.asarray(devices if devices is not None else jax.devices())
        if mesh_shape is None:
            mesh_shape = (devs.size,)
            axis_names = axis_names or ("data",)
        axis_names = tuple(axis_names or Engine.MESH_AXES[:len(mesh_shape)])
        if int(np.prod(mesh_shape)) != devs.size:
            raise ValueError(
                f"mesh shape {tuple(mesh_shape)} does not cover {devs.size} devices")
        return Mesh(devs.reshape(mesh_shape), axis_names)

    @staticmethod
    def default_mesh() -> "jax.sharding.Mesh":
        with _STATE._lock:
            if _STATE._mesh is None:
                _STATE._mesh = Engine.create_mesh()
            return _STATE._mesh

    @staticmethod
    def set_default_mesh(mesh) -> None:
        with _STATE._lock:
            _STATE._mesh = mesh

    # ---- internal -------------------------------------------------------

    @staticmethod
    def _ensure() -> None:
        if not _STATE.inited:
            Engine.init()


def allgather_sum(rows) -> np.ndarray:
    """Sum a small per-process float array across every process (host
    collective; identity single-process).

    The multi-host reduction shared by the distributed metric kinds —
    validation partials (``optim.evaluator``) and aggregated counters
    (``optim.metrics``).  COLLECTIVE: under multi-host every process must
    call it with an array of the same shape."""
    rows = np.asarray(rows, np.float64)
    if jax.process_count() <= 1:
        return rows
    from jax.experimental import multihost_utils
    # process_allgather silently downcasts float64 wires to float32 when
    # jax_enable_x64 is off (the default), which loses integer exactness
    # above 2^24 — e.g. record counts or summed losses on very large
    # validation sets.  Ship each value as a float32 (hi, lo) pair —
    # hi = f32(x), lo = f32(x - hi) — and recombine in float64 after the
    # gather: exact for counts up to ~2^48.
    hi = rows.astype(np.float32)
    lo = (rows - hi.astype(np.float64)).astype(np.float32)
    gathered = np.asarray(
        multihost_utils.process_allgather(np.stack([hi, lo])), np.float64)
    return gathered.sum(axis=(0, 1))


#: dlpack fast-path floor: tiny ride-along tensors (crop offsets, flip
#: flags) gain nothing from capsule plumbing — only batch-scale buffers
#: take the zero-copy leg
_ZERO_COPY_MIN_BYTES = 1 << 16


def _leaf_to_device(x, zero_copy: bool):
    import jax.numpy as jnp

    if (zero_copy and isinstance(x, np.ndarray) and
            x.nbytes >= _ZERO_COPY_MIN_BYTES and
            x.flags["C_CONTIGUOUS"]):
        # dlpack hands the assembler's output buffer straight to the
        # runtime: on CPU backends the device array ALIASES host memory
        # (a true zero-copy), on accelerators the DMA reads the source
        # buffer without the jnp.asarray staging copy.  Safe because
        # every producer on this path (native assembler, pack_batch's
        # np.stack) allocates a fresh buffer per batch and never writes
        # it after handoff.  Never syncs, so the PR 4 host-sync guard
        # stays quiet with this path armed.  Falls back per-array: an
        # exotic dtype/layout the backend rejects just takes the copy.
        try:
            return jnp.from_dlpack(x)
        except (TypeError, ValueError, RuntimeError, BufferError):
            pass                  # backend rejected the capsule: copy path
    return jnp.asarray(x)


def to_device(x):
    """Recursively move a nested list/tuple/dict of arrays onto the device
    (the single host→device crossing point of the data pipeline).

    ``bigdl.ingest.zeroCopyUpload`` (default on) routes large
    C-contiguous numpy leaves through dlpack instead of ``jnp.asarray``,
    eliminating the host-side staging copy between the assembler's
    output buffer and the upload."""
    from bigdl_tpu.utils import config
    zero_copy = config.get_bool("bigdl.ingest.zeroCopyUpload", True)

    def rec(v):
        if isinstance(v, dict):
            return {k: rec(u) for k, u in v.items()}
        if isinstance(v, (list, tuple)):
            return type(v)(rec(u) for u in v)
        return _leaf_to_device(v, zero_copy)

    return rec(x)


def _default_engine_type() -> str:
    try:
        platform = jax.devices()[0].platform
    except Exception:  # pragma: no cover - no devices at all
        platform = "cpu"
    return "tpu" if platform in ("tpu", "axon") else platform
