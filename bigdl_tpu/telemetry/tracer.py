"""Span tracer: thread-safe, allocation-light timeline capture.

The driver hot loop, the streaming-ingest stage threads, the prefetcher's
fetch/transfer threads, and the async checkpoint writer all mark their
work with :func:`span` context managers.  Each thread appends finished
spans to its OWN bounded ring buffer (no cross-thread locking on the hot
path — the global lock is taken once per thread, at ring registration),
and :func:`export_chrome_trace` merges every ring into one Chrome
trace-event JSON (the ``chrome://tracing`` / Perfetto format), one lane
per thread, so a single file shows whether the pipeline stages actually
overlap.

Cost model (the <1%-of-step-time contract ``bench.py --telemetry-only``
measures): disarmed, ``span()`` is one module-dict load plus a shared
no-op context manager — no clock read, no allocation.  Armed, a span
costs two ``monotonic_ns`` reads and one tuple append into a
``deque(maxlen=...)``.  Spans never touch device values — arming the
tracer cannot introduce a host sync (the strict host-sync guard stays
armed over traced runs in the tier-1 suite to prove it).

The tracer's clock — :func:`clock_ns` — is THE timer for hot-path code:
the ``raw-clock-in-hot-path`` lint rule flags direct ``time.*`` reads in
``drain``/``run_step``/``shard_step``/``step`` functions outside this
package, so every duration in the system is measured on one monotonic
clock and two subsystems' timestamps can always be laid on one timeline.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: the one hot-path clock: monotonic (immune to wall-clock steps), ns
#: resolution, same epoch as ``time.monotonic()`` (fractional seconds
#: from legacy call sites convert with a multiply).
clock_ns = time.monotonic_ns

DEFAULT_RING_SIZE = 65536

#: retained per-thread rings; beyond this the oldest DEAD thread's ring
#: is evicted (a long pytest session spawns thousands of short-lived
#: ingest/prefetch threads — their rings must not accumulate forever)
MAX_RINGS = 256

_LOCK = threading.Lock()
_TLS = threading.local()

# armed flag + ring size live in a plain dict: one dict load on the
# disarmed fast path, no attribute-protocol indirection
_STATE: Dict[str, Any] = {"enabled": False,
                          "ring_size": DEFAULT_RING_SIZE,
                          "epoch_ns": 0}

_RINGS: "List[_ThreadRing]" = []
_LANE_SEQ = [0]


class _ThreadRing:
    """One thread's span ring.  ``lane`` is a registration-ordered id
    (thread idents are recycled by the OS; lanes must stay distinct in
    the exported trace), ``events`` holds finished spans as
    ``(name, t0_ns, t1_ns, args)`` tuples — ``t1_ns is None`` marks an
    instant event."""

    __slots__ = ("lane", "name", "thread", "events")

    def __init__(self, lane: int, name: str, thread: threading.Thread,
                 maxlen: int):
        self.lane = lane
        self.name = name
        self.thread = thread
        self.events: deque = deque(maxlen=maxlen)


def _tls_ring() -> _ThreadRing:
    ring = getattr(_TLS, "ring", None)
    if ring is None:
        t = threading.current_thread()
        with _LOCK:
            _LANE_SEQ[0] += 1
            ring = _ThreadRing(_LANE_SEQ[0], t.name, t,
                               _STATE["ring_size"])
            _RINGS.append(ring)
            if len(_RINGS) > MAX_RINGS:
                for i, r in enumerate(_RINGS):
                    if not r.thread.is_alive():
                        del _RINGS[i]
                        break
                else:
                    _RINGS.pop(0)
        _TLS.ring = ring
    return ring


class _NullSpan:
    """Shared no-op context manager: the disarmed ``span()`` result."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "args", "t0")

    def __init__(self, name: str, args: Optional[dict]):
        self.name = name
        self.args = args

    def __enter__(self):
        self.t0 = clock_ns()
        return self

    def __exit__(self, *exc):
        _tls_ring().events.append((self.name, self.t0, clock_ns(),
                                   self.args))
        return False


def tracing_enabled() -> bool:
    return _STATE["enabled"]


def arm(ring_size: Optional[int] = None) -> None:
    """Switch span capture on.  ``ring_size`` bounds each thread's event
    ring (oldest spans fall off first); already-registered rings keep
    their size."""
    with _LOCK:
        if ring_size is not None:
            _STATE["ring_size"] = int(ring_size)
        if not _STATE["enabled"]:
            _STATE["epoch_ns"] = clock_ns()
        _STATE["enabled"] = True


def disarm() -> None:
    _STATE["enabled"] = False


def maybe_arm_from_config() -> bool:
    """Arm iff ``bigdl.telemetry.trace`` is set truthy; never disarms
    (an explicit :func:`arm` — e.g. the test suite's — wins).  Returns
    the resulting enabled state."""
    from bigdl_tpu.utils import config
    if config.get_bool("bigdl.telemetry.trace", False):
        arm(ring_size=config.get_int("bigdl.telemetry.ringSize",
                                     DEFAULT_RING_SIZE))
    return _STATE["enabled"]


def reset() -> None:
    """Drop every captured span (rings stay registered, lanes keep their
    ids).  Test isolation; also the start-of-run hook so one process's
    second training run exports only its own timeline."""
    with _LOCK:
        for ring in _RINGS:
            ring.events.clear()
        _STATE["epoch_ns"] = clock_ns()


def span(name: str, **args):
    """``with telemetry.span("optim/device_step"): ...`` — record the
    enclosed wall interval on this thread's lane.  Free when disarmed."""
    if not _STATE["enabled"]:
        return _NULL_SPAN
    return _Span(name, args or None)


def add_span(name: str, t0_ns: int, t1_ns: int,
             args: Optional[dict] = None) -> None:
    """Record an already-measured interval (both endpoints from
    :func:`clock_ns`).  For call sites that time work anyway (the ingest
    stage counters): no extra clock reads."""
    if _STATE["enabled"]:
        _tls_ring().events.append((name, t0_ns, t1_ns, args))


def add_span_s(name: str, t0_s: float, t1_s: float,
               args: Optional[dict] = None) -> None:
    """:func:`add_span` for endpoints measured with ``time.monotonic()``
    (fractional seconds, same epoch as the ns clock)."""
    if _STATE["enabled"]:
        _tls_ring().events.append((name, int(t0_s * 1e9), int(t1_s * 1e9),
                                   args))


def instant(name: str, **args) -> None:
    """A zero-duration marker on this thread's lane (slow-step flags,
    epoch rollovers)."""
    if _STATE["enabled"]:
        _tls_ring().events.append((name, clock_ns(), None, args or None))


def name_thread(name: str) -> None:
    """Name the current thread's lane in the exported trace (threads
    that were not created with a telling ``Thread(name=...)``)."""
    ring = _tls_ring()
    ring.name = name


def events() -> List[dict]:
    """Every captured span as dicts (diagnostics / tests)."""
    out = []
    with _LOCK:
        rings = [(r.lane, r.name, list(r.events)) for r in _RINGS]
    for lane, lname, evs in rings:
        for name, t0, t1, args in evs:
            out.append({"lane": lane, "thread": lname, "name": name,
                        "t0_ns": t0, "t1_ns": t1, "args": args})
    return out


def export_chrome_trace(path: Optional[str] = None) -> dict:
    """Merge every thread ring into one Chrome trace-event JSON object
    (``{"traceEvents": [...], "displayTimeUnit": "ms"}``), optionally
    written to ``path``.  Loadable by Perfetto / ``chrome://tracing``:
    ``X`` (complete) events carry ``ts``/``dur`` in microseconds relative
    to the arm time, ``M`` metadata events name the process and one lane
    per thread, ``i`` events are instants."""
    epoch = _STATE["epoch_ns"]
    trace_events: List[dict] = [
        {"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
         "args": {"name": "bigdl_tpu"}},
    ]
    with _LOCK:
        rings = [(r.lane, r.name, list(r.events)) for r in _RINGS
                 if r.events]
    for lane, lname, evs in rings:
        trace_events.append({"ph": "M", "name": "thread_name", "pid": 0,
                             "tid": lane, "args": {"name": lname}})
        trace_events.append({"ph": "M", "name": "thread_sort_index",
                             "pid": 0, "tid": lane,
                             "args": {"sort_index": lane}})
        for name, t0, t1, args in evs:
            ev = {"ph": "X" if t1 is not None else "i",
                  "name": name, "cat": name.split("/", 1)[0],
                  "pid": 0, "tid": lane,
                  "ts": (t0 - epoch) / 1e3}
            if t1 is not None:
                ev["dur"] = max(t1 - t0, 0) / 1e3
            else:
                ev["s"] = "t"
            if args:
                ev["args"] = args
            trace_events.append(ev)
    # per-request lanes (telemetry/request_trace.py) merge into the same
    # timeline under their own "requests" process row; lazy import —
    # request_trace imports this module at its top
    from bigdl_tpu.telemetry import request_trace
    trace_events.extend(request_trace.chrome_events(epoch))
    doc = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc
