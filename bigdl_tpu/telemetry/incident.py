"""Incident flight recorder: one artifact per aborted run or terminal fault.

An aborted training run or a shed serving batch used to leave its
evidence scattered across ``Telemetry/*`` gauges, span rings, and 18k
lines of ``bigdl.log``.  This module keeps a **process-global bounded
ring** of structured events fed from the existing subsystems' choke
points — optimizer retry/restore, divergence, replica desync+heal,
watchdog fires, governor shrinks, autoscale/rollout decisions, chaos
injections, preemption signals — and, on any terminal structured
failure (or an explicit :func:`dump`), writes ONE **incident bundle**:

- the event ring (:func:`events`),
- every span lane (:func:`tracer.events`),
- the metrics registry snapshot (``REGISTRY.snapshot()``),
- the effective non-default configuration
  (:func:`~bigdl_tpu.utils.config.non_default_properties`),
- every live thread's stack (``sys._current_frames``),
- and, when applicable, the offending request's trace
  (:func:`~bigdl_tpu.telemetry.request_trace.get`).

Bundles ride the PR 14 disk-full degradation: each write goes through
``file_io.write_bytes`` under ``storage.guarded_export("incident", …)``
(a full disk degrades the recorder with one warning instead of
crashing the failing run a second time), and at most
``bigdl.incident.maxDumps`` bundle files exist per run with
oldest-first eviction — the same bound discipline as
``bounded_timeline_export``.

Signal-safety: :func:`record` is ONE ``deque.append`` under the GIL —
no locks, no IO, no metric-registry touches — so
``elastic.request_preemption`` (the SIGTERM path) may call it.  The
*dump* never runs from signal context; the driver/fleet threads that
observe the preemption flag write the bundle
(:func:`maybe_dump("preemption")`).

Auto-dump discipline: :func:`maybe_dump` writes at most one bundle per
fault slug per run (gated by ``bigdl.incident.autoDump``) — a shed
batch of 32 streams is one incident, not 32 bundle files.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

from bigdl_tpu.telemetry import tracer
from bigdl_tpu.telemetry.metrics import REGISTRY

SCHEMA = "bigdl.incident/1"
DEFAULT_RING_SIZE = 512
DEFAULT_MAX_DUMPS = 8

_LOCK = threading.Lock()
_EVENTS: deque = deque(maxlen=DEFAULT_RING_SIZE)
_DUMPS: List[str] = []          # bundle paths, oldest first
_DUMPED_SLUGS: set = set()      # one auto-dump per fault slug per run
_SEQ = [0]


# ---- the always-on ring ----------------------------------------------------

def record(kind: str, **fields) -> None:
    """Append one structured event to the flight-recorder ring.

    ASYNC-SIGNAL-SAFE by construction: one ``deque.append`` under the
    GIL — no locks, no IO, no metric-registry touches, no allocation
    beyond the event tuple.  Always on (the ring is the cheap part; the
    bundle write is the expensive part and only happens on :func:`dump`).
    """
    _EVENTS.append((tracer.clock_ns(), kind,
                    threading.current_thread().name, fields or None))


def events() -> List[dict]:
    """The event ring as dicts, oldest first."""
    return [{"t_ns": t, "kind": kind, "thread": thread, "fields": fields}
            for t, kind, thread, fields in list(_EVENTS)]


def reset() -> None:
    """Clear the ring, the dump ledger, and the once-per-slug set
    (test isolation / start-of-run); re-reads
    ``bigdl.incident.ringSize`` so tests can resize the ring."""
    global _EVENTS
    from bigdl_tpu.utils import config
    size = max(1, config.get_int("bigdl.incident.ringSize",
                                 DEFAULT_RING_SIZE))
    with _LOCK:
        _EVENTS = deque(maxlen=size)
        del _DUMPS[:]
        _DUMPED_SLUGS.clear()


# ---- the bundle ------------------------------------------------------------

def _thread_stacks() -> Dict[str, List[str]]:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, '?')}-{ident}"
        out[key] = traceback.format_stack(frame)
    return out


def bundle(reason: str, trace_id: Optional[str] = None) -> dict:
    """Assemble (but do not write) one incident bundle."""
    from bigdl_tpu.telemetry import request_trace
    from bigdl_tpu.utils import config
    return {
        "schema": SCHEMA,
        "reason": reason,
        "written_ns": tracer.clock_ns(),
        "events": events(),
        "spans": tracer.events(),
        "metrics": REGISTRY.snapshot(),
        "config": config.non_default_properties(),
        "threads": _thread_stacks(),
        "trace": request_trace.get(trace_id),
        "trace_id": trace_id,
    }


def dump(reason: str, trace_id: Optional[str] = None,
         path: Optional[str] = None) -> Optional[str]:
    """Write ONE incident bundle to disk and return its path.

    Bounded at ``bigdl.incident.maxDumps`` files per run (oldest bundle
    evicted first); the write rides ``guarded_export``/``write_bytes``
    so a full disk degrades the recorder instead of raising.  Returns
    ``None`` when the write was suppressed (cap ≤ 0, storage degraded,
    or the disk filled during the write).
    """
    from bigdl_tpu.resources import storage
    from bigdl_tpu.utils import config, file_io
    cap = config.get_int("bigdl.incident.maxDumps", DEFAULT_MAX_DUMPS)
    if cap <= 0 or storage.is_degraded("incident"):
        return None
    with _LOCK:
        _SEQ[0] += 1
        seq = _SEQ[0]
        while len(_DUMPS) >= cap:
            victim = _DUMPS.pop(0)
            try:
                if os.path.exists(victim):
                    os.unlink(victim)
            except OSError:
                pass
    if path is None:
        base = config.get_property("bigdl.incident.dir") or os.getcwd()
        path = os.path.join(base, f"incident-{seq:04d}.json")
    t0 = tracer.clock_ns()
    doc = bundle(reason, trace_id=trace_id)
    payload = json.dumps(doc, indent=1, sort_keys=True,
                         default=repr).encode("utf-8")

    def _write():
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        file_io.write_bytes(path, payload, overwrite=True)

    if not storage.guarded_export("incident", _write):
        return None
    with _LOCK:
        _DUMPS.append(path)
    dt_ms = (tracer.clock_ns() - t0) / 1e6
    REGISTRY.counter("Incident/dumps",
                     help="incident bundles written").inc()
    REGISTRY.histogram("Incident/dump_ms",
                       help="incident bundle assemble+write latency "
                            "(ms)").observe(dt_ms)
    return path


def maybe_dump(slug: str, trace_id: Optional[str] = None,
               reason: Optional[str] = None) -> Optional[str]:
    """Auto-dump hook for terminal structured failures: writes at most
    one bundle per fault ``slug`` per run, and only when
    ``bigdl.incident.autoDump`` allows (default on).  A shed batch of N
    requests is one incident, not N bundles."""
    from bigdl_tpu.utils import config
    if not config.get_bool("bigdl.incident.autoDump", True):
        return None
    with _LOCK:
        if slug in _DUMPED_SLUGS:
            return None
        _DUMPED_SLUGS.add(slug)
    return dump(reason or slug, trace_id=trace_id)


def dumped() -> List[str]:
    """Paths of the bundles written this run, oldest first."""
    with _LOCK:
        return list(_DUMPS)
