"""Step-time decomposition, rolling latency percentiles, anomaly detection.

Where does the step time go?  The driver loop measures, per iteration:

``data_wait``
    blocking inside ``fetch()`` — time the loop waited on the input
    pipeline (prefetcher queue / synchronous ingest).  Attributed to the
    wall interval the fetch actually ran in: interval ``i`` spans
    ``t0(i) -> t0(i+1)`` and therefore contains iteration ``i+1``'s
    fetch, so a stalled fetch inflates the same interval it is charged
    to (the drain reads it off the next queued item).
``compute``
    the ``run_step`` call: trace + dispatch of the fused jitted step,
    plus — on backends whose dispatch blocks, e.g. the CPU tier-1 mesh —
    the device execution itself.  On fully asynchronous backends the
    overlapped device tail shows up in ``unaccounted`` instead (the
    dispatch-pipelined loop hides it behind later iterations by design).
``host_pull``
    the drain's explicit ``host_pull`` of the iteration loss — the one
    intended device→host round-trip of the hot loop.
``bookkeeping``
    driver-side accounting around the step: metrics adds, the log line,
    summary scalar writes.
``unaccounted``
    the SIGNED residual ``wall − (data_wait + compute + host_pull +
    bookkeeping)``.  Positive residual is time the driver spent outside
    every probe (scheduler preemption, GC, trigger checks); a small
    negative residual means measured segments overlapped the next
    dispatch interval.  Keeping it signed makes the decomposition sum to
    the measured wall time *exactly* — "unaccounted" is a reported
    number, never a hidden fudge.

Wall step time is the inter-dispatch interval the driver already logs
(the pipelined loop's honest per-iteration cost).  Everything here runs
on host floats from the telemetry clock — no device values, so the
accounting can never introduce a host sync.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Dict, List, Optional, Tuple

PARTS = ("data_wait", "compute", "host_pull", "bookkeeping")


class WindowedPercentiles:
    """Exact rolling percentiles over the most recent ``window`` samples
    (numpy linear interpolation — the estimator is *exact* over its
    window, so it degrades by forgetting, never by approximating)."""

    def __init__(self, window: int = 512):
        self._window: deque = deque(maxlen=max(1, int(window)))

    def add(self, value: float) -> None:
        self._window.append(float(value))

    def __len__(self) -> int:
        return len(self._window)

    def percentile(self, q: float) -> float:
        import numpy as np
        if not self._window:
            return float("nan")
        return float(np.percentile(np.asarray(self._window), q))

    def percentiles(self, qs=(50, 95, 99)) -> Dict[int, float]:
        import numpy as np
        if not self._window:
            return {q: float("nan") for q in qs}
        arr = np.asarray(self._window)
        return {q: float(np.percentile(arr, q)) for q in qs}


class SlowStepDetector:
    """Flag steps slower than ``factor`` x the EMA of recent steps.

    Fires at most once per *anomaly window*: the first observation over
    threshold fires, then the detector holds fire until ``cooldown``
    further observations have passed AND a step has landed back under
    threshold — a sustained stall (one long pause spanning many steps, or
    a genuine regime change) reports once, not once per step.  The first
    ``warmup`` observations are only collected; the EMA then seeds from
    their MINIMUM — compile/first-dispatch steps can only inflate a
    warmup window, so the fastest warmup step is the closest thing to a
    steady-state baseline, and ``factor`` (>= 2 in any sane config)
    absorbs the jitter above it.  ``factor <= 0`` disables.
    """

    def __init__(self, factor: float, warmup: int = 5, cooldown: int = 50,
                 alpha: float = 0.1):
        self.factor = float(factor)
        self.warmup = max(0, int(warmup))
        self.cooldown = max(0, int(cooldown))
        self.alpha = alpha
        self.ema: Optional[float] = None
        self.seen = 0
        self.fired = 0
        self._warmup_vals: List[float] = []
        self._cool = 0          # observations left before re-arm
        self._in_window = False

    @property
    def enabled(self) -> bool:
        return self.factor > 0

    def threshold(self) -> float:
        if self.ema is None:
            return math.inf
        return self.factor * self.ema

    def observe(self, value: float) -> bool:
        """Feed one step time; True iff this observation opens a new
        anomaly window (the caller should capture/dump now)."""
        if not self.enabled:
            return False
        self.seen += 1
        if self.seen <= self.warmup:
            self._warmup_vals.append(value)
            return False
        if self.ema is None:
            self.ema = (min(self._warmup_vals) if self._warmup_vals
                        else value)
        slow = value > self.factor * self.ema
        if slow:
            # anomalies do not drag the EMA up: the baseline tracks the
            # healthy regime the threshold is defined against
            fired = not self._in_window and self._cool == 0
            self._in_window = True
            if fired:
                self.fired += 1
                self._cool = self.cooldown
                return True
            return False
        if self._cool > 0:
            self._cool -= 1
        self._in_window = False
        self.ema = (value if self.ema is None
                    else (1 - self.alpha) * self.ema + self.alpha * value)
        return False


class StepAccount:
    """Per-run step accounting: decomposition gauges, rolling latency
    percentiles, and the slow-step detector — all surfaced as
    ``Telemetry/*`` registry metrics the driver's single emission loop
    charts into TrainSummary."""

    def __init__(self, window: int = 512,
                 detector: Optional[SlowStepDetector] = None):
        from bigdl_tpu.telemetry.metrics import REGISTRY
        self._reg = REGISTRY
        self.detector = detector or SlowStepDetector(0.0)
        self.steps = 0
        self.totals_ns: Dict[str, float] = {p: 0.0 for p in PARTS}
        self.totals_ns["unaccounted"] = 0.0
        self.totals_ns["wall"] = 0.0
        self.last: Dict[str, float] = {}
        # the registry histogram IS the rolling wall-latency window —
        # percentile reads come from it, one copy of the samples
        self._hist = REGISTRY.histogram(
            "Telemetry/step_latency_ms", window=window,
            help="wall step time (inter-dispatch interval)")

    def account(self, wall_ns: int, **parts_ns: float) -> bool:
        """Fold one finished iteration in.  ``parts_ns`` maps any subset
        of :data:`PARTS` to nanoseconds; the signed remainder becomes
        ``unaccounted``.  Returns True when this step opened a slow-step
        anomaly window."""
        wall_ns = max(int(wall_ns), 0)
        decomp = {p: float(parts_ns.get(p, 0.0)) for p in PARTS}
        decomp["unaccounted"] = wall_ns - sum(decomp.values())
        decomp["wall"] = float(wall_ns)
        self.steps += 1
        for k, v in decomp.items():
            self.totals_ns[k] += v
        self.last = decomp
        self._hist.observe(wall_ns / 1e6)
        g = self._reg.gauge
        for p in PARTS + ("unaccounted",):
            g(f"Telemetry/{p}_ms", summary=True).set(decomp[p] / 1e6)
        g("Telemetry/step_ms", summary=True).set(wall_ns / 1e6)
        fired = self.detector.observe(float(wall_ns))
        if self.detector.enabled:
            g("Telemetry/slow_steps", summary=True).set(self.detector.fired)
        return fired

    def percentile_scalars(self) -> List[Tuple[str, float]]:
        """Rolling p50/p95/p99 wall latency in ms, as summary pairs.
        Computed lazily (one small sort per call) so runs without a
        TrainSummary never pay for it."""
        return [(f"Telemetry/step_p{q}_ms", self._hist.percentile(q))
                for q in (50, 95, 99) if self._hist.count]

    def summary(self) -> dict:
        """End-of-run roll-up (for ``telemetry.json`` / logs): mean
        decomposition shares plus latency percentiles."""
        if not self.steps:
            return {"steps": 0}
        wall = self.totals_ns["wall"] or 1.0
        out = {"steps": self.steps,
               "mean_step_ms": wall / self.steps / 1e6,
               "slow_steps": self.detector.fired}
        for p in PARTS + ("unaccounted",):
            out[f"{p}_frac"] = self.totals_ns[p] / wall
            out[f"{p}_ms_mean"] = self.totals_ns[p] / self.steps / 1e6
        st = self._hist.stats()
        for q in (50, 95, 99):
            v = st.get(f"p{q}")
            if v is not None and not math.isnan(v):
                out[f"p{q}_ms"] = v
        return out


def step_flops(lowered) -> Optional[float]:
    """Pull the per-step FLOP count out of a ``jax.stages.Lowered`` cost
    analysis (no XLA compile — the estimate comes from the lowered HLO).
    None when the backend/version exposes nothing usable."""
    try:
        ca = lowered.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):       # older jax: one dict per device
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    if flops is None or not math.isfinite(flops) or flops <= 0:
        return None
    return float(flops)
