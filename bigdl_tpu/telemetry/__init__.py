"""Runtime telemetry: span tracing, step-time decomposition, metrics.

The reference's observability was a throughput log line per iteration
(``optim/DistriOptimizer.scala:293-297``); the rebuild's driver-centric
loop needs to answer *where the step time goes* without printf
archaeology.  Three pillars, one package:

1. **Span tracer** (:mod:`~bigdl_tpu.telemetry.tracer`) —
   ``with telemetry.span("optim/device_step"): ...`` writes to per-thread
   ring buffers; :func:`export_chrome_trace` merges the driver hot loop,
   every ``StreamingIngest`` stage thread, the ``BatchPrefetcher``
   fetch/transfer threads, the async checkpoint writer, and the
   compile-warmup phase (``driver/compile_warmup`` wrapping one
   ``compile/<step>`` span per trace/cache-load/compile, from
   ``utils/compile_cache``) into one Perfetto-loadable timeline.  Free
   when disarmed; allocation-light and device-value-free when armed
   (the strict host-sync guard stays green over traced runs).
2. **Step-time decomposition** (:mod:`~bigdl_tpu.telemetry.step_stats`)
   — every optimizer step is accounted into data-wait / compute /
   host-pull / bookkeeping plus an explicit signed ``unaccounted``
   residual, surfaced as ``Telemetry/*`` TrainSummary scalars with
   rolling p50/p95/p99 latency; a slow-step detector (step > k·EMA) can
   trigger an on-demand ``jax.profiler`` capture and a timeline dump.
3. **Metrics registry** (:mod:`~bigdl_tpu.telemetry.metrics`) —
   counters/gauges/histograms with labeled names, ONE summary flush path
   (the driver's single emission loop), a per-run ``telemetry.json``
   snapshot, and a Prometheus text dump.  The pre-existing ``Ingest/*``
   and ``Analysis/*`` scalars route through it with unchanged tags.

Two forensic layers ride the pillars: per-request distributed tracing
(:mod:`~bigdl_tpu.telemetry.request_trace` — a trace id per serving/LM/
fleet submission, a causally-ordered span chain ending in the terminal
verdict, histogram exemplars for tail-latency lookup) and the incident
flight recorder (:mod:`~bigdl_tpu.telemetry.incident` — a bounded
structured-event ring plus one self-contained bundle per terminal
fault).

Configuration (``bigdl.telemetry.*`` / ``bigdl.trace.*`` /
``bigdl.incident.*`` in ``utils/config.py``); the knob table lives in
``docs/programming-guide/optimization.md``.
"""

from __future__ import annotations

from bigdl_tpu.telemetry.tracer import (add_span, add_span_s, arm, clock_ns,
                                        disarm, events, export_chrome_trace,
                                        instant, maybe_arm_from_config,
                                        name_thread, span, tracing_enabled)
from bigdl_tpu.telemetry.tracer import reset as reset_tracer
from bigdl_tpu.telemetry.metrics import (Counter, Gauge, Histogram,
                                         MetricsRegistry, REGISTRY)
from bigdl_tpu.telemetry.step_stats import (PARTS, SlowStepDetector,
                                            StepAccount, WindowedPercentiles,
                                            step_flops)
from bigdl_tpu.telemetry import incident, request_trace


def counter(name, labels=None, summary=False, help=""):
    """Shorthand for ``REGISTRY.counter(...)``."""
    return REGISTRY.counter(name, labels=labels, summary=summary, help=help)


def gauge(name, labels=None, summary=False, help=""):
    """Shorthand for ``REGISTRY.gauge(...)``."""
    return REGISTRY.gauge(name, labels=labels, summary=summary, help=help)


def histogram(name, labels=None, summary=False, help="", window=512):
    """Shorthand for ``REGISTRY.histogram(...)``."""
    return REGISTRY.histogram(name, labels=labels, summary=summary,
                              help=help, window=window)


def summary_scalars():
    """The one flush path: every chartable ``(tag, value)`` pair."""
    return REGISTRY.summary_scalars()


__all__ = [
    # tracer
    "span", "instant", "add_span", "add_span_s", "clock_ns", "arm",
    "disarm", "tracing_enabled", "maybe_arm_from_config", "name_thread",
    "events", "export_chrome_trace", "reset_tracer",
    # metrics
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "counter", "gauge", "histogram", "summary_scalars",
    # step stats
    "PARTS", "StepAccount", "WindowedPercentiles", "SlowStepDetector",
    "step_flops",
    # per-request tracing + incident flight recorder
    "request_trace", "incident",
]
