"""Metrics registry: one naming scheme, one flush path for every counter.

Before this module each subsystem invented its own surface — ad-hoc
``stats()`` dicts in the ingest engine, raw attributes on the
prefetcher, module-global counters in ``analysis`` — and the driver loop
grew one bespoke emission loop per subsystem.  The registry gives them a
single home:

- :class:`Counter` (monotonic), :class:`Gauge` (set-to-latest), and
  :class:`Histogram` (count/sum/min/max plus exact percentiles over a
  bounded sample window), each with optional ``{label: value}`` labels;
- metrics created with ``summary=True`` are charted: the driver's ONE
  emission loop (``Optimizer._summarize_train``) walks
  :meth:`MetricsRegistry.summary_scalars` and writes each pair as a
  TrainSummary scalar under its registry name — which is therefore the
  TensorBoard tag, so the documented metric table (``docs/programming-
  guide/visualization.md``) is the single source of naming truth;
- subsystems whose values are snapshots of live state (the ingest
  engine's per-stage throughput) register a *provider* callable instead
  of pushing, and the same emission loop pulls it;
- :meth:`snapshot` serializes everything to the per-run
  ``telemetry.json``; :meth:`prometheus_text` renders the same data as a
  Prometheus text-format dump for scrape-style collection.

Thread-safety: one registry lock around the name table; each metric
carries its own lock so hot-path ``inc``/``observe`` from stage threads
never contend on the registry itself.
"""

from __future__ import annotations

import bisect
import json
import logging
import math
import re
import threading
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

logger = logging.getLogger("bigdl_tpu")

#: unbounded label cardinality is a slow host-memory leak (and a
#: Prometheus scrape bomb): past this many labeled variants of one base
#: name, further variants fold into a single ``{overflow="true"}``
#: series instead of minting new ones
MAX_LABEL_VARIANTS = 64


def _label_key(name: str, labels: Optional[dict]) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Prometheus metric names cannot carry ``/``-style paths; fold every
    illegal character to ``_`` (``Ingest/read/throughput`` →
    ``Ingest_read_throughput``)."""
    return _PROM_BAD.sub("_", name)


def _prom_escape(value) -> str:
    """Label-VALUE escaping per the exposition format: backslash, double
    quote, and newline must be escaped inside ``{k="v"}``."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


#: default histogram bucket upper bounds — a log-ish ladder sized for the
#: millisecond-latency histograms this registry actually holds
#: (``Serving/latency_ms``, ``LM/ttft_ms``, ``Telemetry/step_latency_ms``);
#: the +Inf bucket is implicit
DEFAULT_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                   500.0, 1000.0, 2500.0, 5000.0, 10000.0)

#: exemplar trace ids retained per histogram: the K largest observations
#: that carried one (tail-bucket forensics — "show me a p99 request")
MAX_EXEMPLARS = 8


class _Metric:
    kind = "metric"

    def __init__(self, name: str, labels: Optional[dict], summary: bool,
                 help: str = ""):
        self.name = name
        self.labels = dict(labels) if labels else None
        self.summary = summary
        self.help = help
        self._lock = threading.Lock()


class Counter(_Metric):
    """Monotonically increasing count (items decoded, slow steps, …)."""

    kind = "counter"

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._value = 0.0

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Metric):
    """Set-to-latest value (ring occupancy, current decomposition ms)."""

    kind = "gauge"

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Metric):
    """Streaming distribution: exact count/sum/min/max over the full
    stream plus exact percentiles over the most recent ``window``
    observations (the rolling-window estimator the step-latency
    p50/p95/p99 ride on — see :class:`~bigdl_tpu.telemetry.step_stats.
    WindowedPercentiles` for the standalone form).  Also keeps
    Prometheus-conformant cumulative bucket counts (fixed ``le`` ladder
    plus the implicit ``+Inf``) and bounded **exemplars**: observations
    tagged with a request trace id retain the ``MAX_EXEMPLARS`` largest
    ``(value, trace_id)`` pairs, so a tail-bucket latency resolves to a
    real request in one lookup (:meth:`tail_exemplar`)."""

    kind = "histogram"

    def __init__(self, name, labels=None, summary=False, help="",
                 window: int = 512, buckets=DEFAULT_BUCKETS):
        super().__init__(name, labels, summary, help)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._window: deque = deque(maxlen=max(1, int(window)))
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # one slot per finite bound + the +Inf slot; rendered cumulative
        self._bucket_counts = [0] * (len(self.buckets) + 1)
        self._exemplars: List[Tuple[float, str]] = []

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        value = float(value)
        with self._lock:
            self.count += 1
            self.sum += value
            self.min = min(self.min, value)
            self.max = max(self.max, value)
            self._window.append(value)
            self._bucket_counts[bisect.bisect_left(self.buckets,
                                                   value)] += 1
            if exemplar is not None:
                self._exemplars.append((value, exemplar))
                if len(self._exemplars) > MAX_EXEMPLARS:
                    self._exemplars.sort(key=lambda p: p[0], reverse=True)
                    del self._exemplars[MAX_EXEMPLARS:]

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """Cumulative ``(le_bound, count)`` pairs, ``math.inf`` last —
        exactly what the ``_bucket{le=...}`` series render."""
        with self._lock:
            raw = list(self._bucket_counts)
        out, running = [], 0
        for bound, n in zip(self.buckets + (math.inf,), raw):
            running += n
            out.append((bound, running))
        return out

    def exemplars(self) -> List[Tuple[float, str]]:
        """Retained ``(value, trace_id)`` pairs, largest value first."""
        with self._lock:
            return sorted(self._exemplars, key=lambda p: p[0],
                          reverse=True)

    def tail_exemplar(self) -> Optional[str]:
        """The trace id of the largest observation that carried one —
        the "show me a p99 request" entry point."""
        ex = self.exemplars()
        return ex[0][1] if ex else None

    def percentile(self, q: float) -> float:
        """Exact percentile (numpy's linear interpolation) over the
        retained window; NaN before the first observation."""
        import numpy as np
        with self._lock:
            if not self._window:
                return float("nan")
            return float(np.percentile(np.asarray(self._window), q))

    @property
    def value(self) -> float:
        with self._lock:
            return self.sum / self.count if self.count else 0.0

    def stats(self) -> dict:
        with self._lock:
            if not self.count:
                return {"count": 0, "sum": 0.0}
            out = {"count": self.count, "sum": self.sum,
                   "min": self.min, "max": self.max,
                   "mean": self.sum / self.count}
            exemplars = sorted(self._exemplars, key=lambda p: p[0],
                               reverse=True)
        for q in (50, 95, 99):
            out[f"p{q}"] = self.percentile(q)
        if exemplars:
            out["exemplars"] = [[v, tid] for v, tid in exemplars]
        return out


class MetricsRegistry:
    """The process-wide metric table (module singleton ``REGISTRY``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "Dict[str, _Metric]" = {}
        self._providers: "Dict[str, Callable[[], Iterable[Tuple[str, float]]]]" = {}
        self._label_variants: Dict[str, int] = {}
        self._overflow_logged: set = set()

    # ---- creation (get-or-create, keyed on name + labels) ---------------

    def _get_or_create(self, cls, name: str, labels: Optional[dict],
                       summary: bool, help: str, **kw) -> _Metric:
        key = _label_key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None and labels and (
                    self._label_variants.get(name, 0) >=
                    MAX_LABEL_VARIANTS):
                # cardinality cap: fold this NEW variant into the
                # overflow series (existing variants keep updating)
                labels = {"overflow": "true"}
                key = _label_key(name, labels)
                m = self._metrics.get(key)
                if name not in self._overflow_logged:
                    self._overflow_logged.add(name)
                    logger.warning(
                        "metric %r reached %d label variants — further "
                        "variants fold into its {overflow=\"true\"} "
                        "series", name, MAX_LABEL_VARIANTS)
            if m is None:
                m = cls(name, labels=labels, summary=summary, help=help,
                        **kw)
                self._metrics[key] = m
                if labels:
                    self._label_variants[name] = (
                        self._label_variants.get(name, 0) + 1)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {key!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            if summary:
                m.summary = True
            return m

    def counter(self, name: str, labels: Optional[dict] = None,
                summary: bool = False, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, labels, summary, help)

    def gauge(self, name: str, labels: Optional[dict] = None,
              summary: bool = False, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, labels, summary, help)

    def histogram(self, name: str, labels: Optional[dict] = None,
                  summary: bool = False, help: str = "",
                  window: int = 512) -> Histogram:
        return self._get_or_create(Histogram, name, labels, summary, help,
                                   window=window)

    # ---- providers -------------------------------------------------------

    def register_provider(
            self, name: str,
            fn: Callable[[], Iterable[Tuple[str, float]]]) -> None:
        """Register a pull-mode scalar source: ``fn()`` yields
        ``(tag, value)`` pairs when the summary loop (or a snapshot)
        asks.  Re-registering a name replaces the provider (module
        reloads in tests)."""
        with self._lock:
            self._providers[name] = fn

    def unregister_provider(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    # ---- the one flush path ---------------------------------------------

    def summary_scalars(self) -> List[Tuple[str, float]]:
        """Every chartable ``(tag, value)`` pair: summary-flagged metrics
        (labels folded into the tag) followed by every provider's pairs.
        THE single emission loop in the driver iterates exactly this."""
        with self._lock:
            metrics = list(self._metrics.items())
            providers = list(self._providers.values())
        out: List[Tuple[str, float]] = []
        for key, m in metrics:
            if m.summary:
                out.append((key, m.value))
        for fn in providers:
            out.extend(fn())
        return out

    def snapshot(self) -> dict:
        """JSON-serializable dump of the whole registry (the per-run
        ``telemetry.json`` artifact).  Round-trips through
        ``json.dumps``/``loads`` unchanged."""
        with self._lock:
            metrics = list(self._metrics.items())
            providers = list(self._providers.items())
        counters, gauges, histograms = {}, {}, {}
        for key, m in metrics:
            if isinstance(m, Histogram):
                histograms[key] = m.stats()
            elif isinstance(m, Counter):
                counters[key] = m.value
            else:
                gauges[key] = m.value
        provided = {}
        for name, fn in providers:
            provided.update({tag: float(v) for tag, v in fn()})
        return {"counters": counters, "gauges": gauges,
                "histograms": histograms, "provided": provided}

    def write_snapshot(self, path: str) -> dict:
        snap = self.snapshot()
        with open(path, "w") as f:
            json.dump(snap, f, indent=1, sort_keys=True)
        return snap

    def prometheus_text(self) -> str:
        """The registry in Prometheus exposition text format: names
        sanitized, label VALUES escaped (backslash / quote / newline),
        one ``# TYPE`` line per metric; histograms emit the conformant
        cumulative ``_bucket{le=...}`` series ending at ``le="+Inf"``
        plus ``_sum``/``_count``."""
        with self._lock:
            metrics = list(self._metrics.values())
            providers = list(self._providers.items())
        lines: List[str] = []
        typed: set = set()

        def fmt(name, labels, value):
            if labels:
                inner = ",".join(
                    f'{_prom_name(k)}="{_prom_escape(labels[k])}"'
                    for k in sorted(labels))
                return f"{name}{{{inner}}} {value}"
            return f"{name} {value}"

        def type_line(pname, kind, help_text):
            # one # TYPE (and at most one # HELP) per metric name even
            # when label variants share it — the format forbids repeats
            if pname in typed:
                return
            typed.add(pname)
            if help_text:
                lines.append(f"# HELP {pname} {help_text}")
            lines.append(f"# TYPE {pname} {kind}")

        for m in metrics:
            pname = _prom_name(m.name)
            type_line(pname, m.kind, m.help)
            if isinstance(m, Histogram):
                for bound, cum in m.bucket_counts():
                    labels = dict(m.labels or {})
                    labels["le"] = ("+Inf" if math.isinf(bound)
                                    else repr(bound))
                    lines.append(fmt(f"{pname}_bucket", labels, cum))
                lines.append(fmt(f"{pname}_sum", m.labels, m.sum))
                lines.append(fmt(f"{pname}_count", m.labels, m.count))
            else:
                lines.append(fmt(pname, m.labels, m.value))
        for name, fn in providers:
            for tag, v in fn():
                lines.append(fmt(_prom_name(tag), None, float(v)))
        return "\n".join(lines) + "\n"

    # ---- lifecycle -------------------------------------------------------

    def drop_prefix(self, prefix: str) -> None:
        """Remove every metric whose name starts with ``prefix`` — the
        start-of-run hook that keeps one process's second training run
        from re-emitting a previous run's ``Analysis/*``/``Telemetry/*``
        gauges under stale values."""
        with self._lock:
            for key in [k for k, m in self._metrics.items()
                        if m.name.startswith(prefix)]:
                del self._metrics[key]
            for name in [n for n in self._label_variants
                         if n.startswith(prefix)]:
                del self._label_variants[name]
                self._overflow_logged.discard(name)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._providers.clear()
            self._label_variants.clear()
            self._overflow_logged.clear()


REGISTRY = MetricsRegistry()
