"""Per-request distributed tracing: every request explains itself.

The serving/fleet stack (PRs 9/17/18) classifies every submission into
the accounting identity ``completed + shed + rejected + quarantined ==
submitted`` — but the classes are *anonymous*: a p99 outlier or a shed
stream cannot be followed through admission → queue → batcher →
prefill → decode → fleet replica.  This module adds the missing
identity:

- :func:`mint` assigns a trace id at the admission door (both
  ``ServingEngine.submit`` and ``LMServingEngine.submit``, plus the
  fleet's own pre-engine rejections);
- :func:`record_span` / :func:`span` accumulate a causally-ordered span
  chain per request (``request/queue_wait``, ``request/coalesce``,
  ``request/dispatch``, ``request/prefill``, ``request/decode_step``,
  ``request/emit`` …) and mirror each span onto the PR 5 per-thread
  rings (:mod:`~bigdl_tpu.telemetry.tracer`) so the Chrome trace shows
  the same work from both the thread and the request point of view;
- :func:`verdict` records the request's terminal outcome exactly once
  (first-wins, mirroring ``RequestHandle._finish``), stamps the trace id
  onto the structured error (``error.trace_id``), and bumps the
  ``Trace/verdicts`` counter;
- :func:`chrome_events` merges every traced request into
  :func:`~bigdl_tpu.telemetry.tracer.export_chrome_trace` as a
  ``request:<id>`` lane under a dedicated ``requests`` process row.

Tail-latency forensics ride **exemplars**: the ``Serving/latency_ms``
and ``LM/*`` histograms record the trace ids of their largest
observations (:meth:`~bigdl_tpu.telemetry.metrics.Histogram.observe`
with ``exemplar=``), so "show me a p99 request" is
``hist.tail_exemplar()`` → :func:`get` — one lookup.

Cost model (the ``bench.py --trace-only`` contract: armed < 1% of the
serving p50, disarmed ≤ 0.25%): disarmed, :func:`mint` is one dict load
returning ``None`` and every recorder no-ops on a ``None`` id; armed, a
span is one tuple append onto the trace's bounded list plus the tracer
ring mirror.  The registry is bounded two ways: at most
``bigdl.trace.maxTraces`` retained traces (oldest evicted first) and at
most ``bigdl.trace.maxSpansPerTrace`` spans per trace (the trace is
flagged ``truncated`` instead of growing without bound).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from bigdl_tpu.telemetry import tracer
from bigdl_tpu.telemetry.metrics import REGISTRY

DEFAULT_MAX_TRACES = 2048
DEFAULT_MAX_SPANS = 512

#: terminal outcomes a trace may end in — the serving taxonomy's
#: ``OUTCOMES`` plus the fleet-side ``aborted`` (replica crash /
#: abandoned handle)
VERDICTS = ("completed", "shed", "rejected", "quarantined", "aborted")

_LOCK = threading.Lock()

# armed flag + bounds in a plain dict: one dict load on the disarmed
# fast path (same idiom as tracer._STATE)
_STATE: Dict[str, Any] = {"enabled": False,
                          "max_traces": DEFAULT_MAX_TRACES,
                          "max_spans": DEFAULT_MAX_SPANS}

_SEQ = [0]
_TRACES: "OrderedDict[str, _Trace]" = OrderedDict()

#: labeled-counter cache: the registry lookup (name + label variant)
#: costs ~10x the increment itself, so the admission/verdict hot paths
#: resolve each (metric, label) pair once.  Cleared by :func:`reset`
#: (which every test teardown calls), so a registry reset in a test
#: cannot leave a detached counter past the test that did it.
_COUNTERS: Dict[tuple, Any] = {}


def _counter(name: str, label_key: str, label_val: str, help: str):
    key = (name, label_val)
    c = _COUNTERS.get(key)
    if c is None:
        c = REGISTRY.counter(name, labels={label_key: label_val},
                             help=help)
        _COUNTERS[key] = c
    return c


class _Trace:
    """One request's record.  ``spans`` holds ``(name, t0_ns, t1_ns,
    args)`` tuples (``t1_ns is None`` marks an instant); appends are
    GIL-atomic so recorders on batcher/decode threads never take the
    registry lock on the hot path."""

    __slots__ = ("trace_id", "seq", "kind", "created_ns", "attrs",
                 "spans", "verdict", "error", "reason", "truncated")

    def __init__(self, trace_id: str, seq: int, kind: str,
                 created_ns: int, attrs: Optional[dict]):
        self.trace_id = trace_id
        self.seq = seq
        self.kind = kind
        self.created_ns = created_ns
        self.attrs = attrs
        self.spans: List[tuple] = []
        self.verdict: Optional[str] = None
        self.error: Optional[str] = None
        self.reason: Optional[str] = None
        self.truncated = False


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _ReqSpan:
    __slots__ = ("trace_id", "name", "args", "t0")

    def __init__(self, trace_id: str, name: str, args: Optional[dict]):
        self.trace_id = trace_id
        self.name = name
        self.args = args

    def __enter__(self):
        self.t0 = tracer.clock_ns()
        return self

    def __exit__(self, *exc):
        record_span(self.trace_id, self.name, self.t0, tracer.clock_ns(),
                    **(self.args or {}))
        return False


# ---- arming ----------------------------------------------------------------

def enabled() -> bool:
    return _STATE["enabled"]


def arm(max_traces: Optional[int] = None,
        max_spans: Optional[int] = None) -> None:
    """Switch per-request trace capture on.  Bounds default to the
    ``bigdl.trace.maxTraces`` / ``bigdl.trace.maxSpansPerTrace``
    configuration when not given explicitly."""
    from bigdl_tpu.utils import config
    with _LOCK:
        _STATE["max_traces"] = int(
            max_traces if max_traces is not None else
            config.get_int("bigdl.trace.maxTraces", DEFAULT_MAX_TRACES))
        _STATE["max_spans"] = int(
            max_spans if max_spans is not None else
            config.get_int("bigdl.trace.maxSpansPerTrace",
                           DEFAULT_MAX_SPANS))
        _STATE["enabled"] = True


def disarm() -> None:
    _STATE["enabled"] = False


def maybe_arm_from_config() -> bool:
    """Arm iff ``bigdl.trace.requests`` is set truthy; never disarms
    (an explicit :func:`arm` wins).  Returns the resulting state."""
    from bigdl_tpu.utils import config
    if config.get_bool("bigdl.trace.requests", False):
        arm()
    return _STATE["enabled"]


def reset() -> None:
    """Drop every retained trace (test isolation / start-of-run)."""
    with _LOCK:
        _TRACES.clear()
        _COUNTERS.clear()


# ---- recording -------------------------------------------------------------

def mint(kind: str = "req", **attrs) -> Optional[str]:
    """Assign a trace id at the admission door.  Returns ``None`` when
    request tracing is disarmed — every recorder below no-ops on
    ``None``, so call sites thread the id unconditionally."""
    if not _STATE["enabled"]:
        return None
    now = tracer.clock_ns()
    with _LOCK:
        _SEQ[0] += 1
        seq = _SEQ[0]
        trace_id = f"{kind}-{seq:06d}"
        _TRACES[trace_id] = _Trace(trace_id, seq, kind, now, attrs or None)
        while len(_TRACES) > _STATE["max_traces"]:
            _TRACES.popitem(last=False)
    _counter("Trace/minted", "kind", kind,
             "request trace ids minted at admission").inc()
    return trace_id


def record_span(trace_id: Optional[str], name: str, t0_ns: int,
                t1_ns: Optional[int], **args) -> None:
    """Append one causally-ordered span to ``trace_id``'s chain and
    mirror it onto this thread's tracer ring (tagged with the id so the
    thread lanes and the ``request:`` lane cross-reference)."""
    if trace_id is None:
        return
    tr = _TRACES.get(trace_id)
    if tr is None:
        return
    if len(tr.spans) < _STATE["max_spans"]:
        tr.spans.append((name, t0_ns, t1_ns, args or None))
    else:
        tr.truncated = True
    if tracer.tracing_enabled():    # skip the args-dict build when the
        tracer.add_span(name, t0_ns, t1_ns,   # thread rings are off
                        dict(args, trace_id=trace_id))


def instant(trace_id: Optional[str], name: str, **args) -> None:
    """A zero-duration marker on the trace (admission, verdict …)."""
    record_span(trace_id, name, tracer.clock_ns(), None, **args)


def span(trace_id: Optional[str], name: str, **args):
    """``with request_trace.span(tid, "request/dispatch"): ...`` —
    free when disarmed or untraced."""
    if trace_id is None or not _STATE["enabled"]:
        return _NULL_SPAN
    return _ReqSpan(trace_id, name, args or None)


def tag_error(error: Optional[BaseException],
              trace_id: Optional[str]) -> None:
    """Stamp the trace id onto a structured error so every
    ``Overloaded``/``DeadlineExceeded``/… carries its request identity
    to whoever catches it."""
    if error is None or trace_id is None:
        return
    try:
        error.trace_id = trace_id
    except AttributeError:      # __slots__-restricted exception
        pass


def verdict(trace_id: Optional[str], outcome: str,
            error: Optional[BaseException] = None,
            reason: Optional[str] = None) -> bool:
    """Record the request's terminal verdict — exactly once (first
    wins, mirroring the engines' ``_finish`` discipline).  Returns True
    when this call was the one that terminated the trace."""
    tag_error(error, trace_id)
    if trace_id is None:
        return False
    tr = _TRACES.get(trace_id)
    if tr is None:
        return False
    with _LOCK:
        if tr.verdict is not None:
            return False
        tr.verdict = outcome
        tr.error = repr(error) if error is not None else None
        tr.reason = reason
    args = {"outcome": outcome}
    if reason:
        args["reason"] = reason
    record_span(trace_id, "request/verdict", tracer.clock_ns(), None,
                **args)
    _counter("Trace/verdicts", "outcome", outcome,
             "terminal request verdicts recorded").inc()
    return True


# ---- reading ---------------------------------------------------------------

def _as_dict(tr: _Trace) -> dict:
    spans = [{"name": name, "t0_ns": t0, "t1_ns": t1, "args": args}
             for name, t0, t1, args in
             sorted(list(tr.spans), key=lambda s: s[1])]
    return {"trace_id": tr.trace_id, "kind": tr.kind,
            "created_ns": tr.created_ns, "attrs": tr.attrs,
            "verdict": tr.verdict, "error": tr.error,
            "reason": tr.reason, "truncated": tr.truncated,
            "spans": spans}


def get(trace_id: Optional[str]) -> Optional[dict]:
    """The request's causally-ordered span chain (spans sorted by start
    time) plus its terminal verdict, as one JSON-serializable dict."""
    if trace_id is None:
        return None
    tr = _TRACES.get(trace_id)
    if tr is None:
        return None
    with _LOCK:
        return _as_dict(tr)


def traces() -> List[dict]:
    """Every retained trace (diagnostics / the incident bundle)."""
    with _LOCK:
        return [_as_dict(tr) for tr in _TRACES.values()]


def chrome_events(epoch_ns: int) -> List[dict]:
    """Every traced request as a ``request:<id>`` lane under its own
    ``requests`` process row — merged by
    :func:`~bigdl_tpu.telemetry.tracer.export_chrome_trace`."""
    with _LOCK:
        recs = [(tr.seq, tr.trace_id, tr.verdict, list(tr.spans))
                for tr in _TRACES.values() if tr.spans]
    if not recs:
        return []
    out: List[dict] = [
        {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
         "args": {"name": "requests"}},
    ]
    for seq, trace_id, final, spans in recs:
        lane_name = f"request:{trace_id}"
        if final:
            lane_name += f" [{final}]"
        out.append({"ph": "M", "name": "thread_name", "pid": 1,
                    "tid": seq, "args": {"name": lane_name}})
        for name, t0, t1, args in spans:
            ev = {"ph": "X" if t1 is not None else "i",
                  "name": name, "cat": "request", "pid": 1, "tid": seq,
                  "ts": (t0 - epoch_ns) / 1e3}
            if t1 is not None:
                ev["dur"] = max(t1 - t0, 0) / 1e3
            else:
                ev["s"] = "t"
            ev["args"] = dict(args or {}, trace_id=trace_id)
            out.append(ev)
    return out
