"""Host-memory governor: byte accounting + soft-budget backpressure.

Every bounded buffer the ingest / prefetch / serving paths own (record
ring, decode in-flight window, batch ring, transfer-ahead slots,
quarantine samples, admission queue) registers an :class:`Account` here
and keeps it current as items enter and leave.  The roll-up is exported
as ``Resources/host_bytes`` through the telemetry registry provider
mechanism (PR 5), one gauge per account plus the total.

A soft budget ``bigdl.resources.hostMemBudgetMB`` (0 = accounting only,
no enforcement) turns the governor active: when the accounted total
reaches the budget — or the chaos injector
``bigdl.chaos.hostMemPressureAt`` clamps the reported free bytes at the
k-th poll — the registered *shrinkers* fire (ring depth halving, pause
of read-ahead) through the same backpressure machinery the pipelines
already have, instead of letting the process OOM.  Shrinks persist for
the rest of the run; pressure detection is edge-triggered so a sustained
breach fires the shrinkers once per excursion, not once per poll.

When even a single item exceeds the whole budget there is no depth left
to shrink: :meth:`HostMemoryGovernor.check_item` raises the structured
:class:`~bigdl_tpu.resources.errors.HostMemoryError` escalation.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Iterable, Tuple

from bigdl_tpu.resources.errors import HostMemoryError

logger = logging.getLogger("bigdl_tpu")


class Account:
    """One named byte ledger (a ring, a window, a queue).  Thread-safe;
    clamped at zero so a stray double-subtract cannot go negative and
    poison the roll-up."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._bytes = 0

    @property
    def nbytes(self) -> int:
        return self._bytes

    def add(self, n: int) -> None:
        with self._lock:
            self._bytes += max(0, int(n))

    def sub(self, n: int) -> None:
        with self._lock:
            self._bytes = max(0, self._bytes - max(0, int(n)))

    def set(self, n: int) -> None:
        with self._lock:
            self._bytes = max(0, int(n))


class HostMemoryGovernor:
    """Process-wide ledger of accounted host buffers + the soft-budget
    reaction (shrinkers) and the hard escalation (HostMemoryError)."""

    def __init__(self):
        from bigdl_tpu import analysis
        self._lock = analysis.make_lock("governor.host")
        self._accounts: Dict[str, Account] = {}
        self._shrinkers: Dict[str, Callable[[], None]] = {}
        self._polls = 0
        self._pressure_events = 0
        self._under_pressure = False

    # ---- accounts ------------------------------------------------------

    def account(self, name: str) -> Account:
        """Get-or-create the named ledger (idempotent: stages re-created
        across epochs reuse their account)."""
        with self._lock:
            acct = self._accounts.get(name)
            if acct is None:
                from bigdl_tpu import analysis
                # every Account shares one witness name: account locks are
                # leaves (never nested), so collapsing them keeps the
                # order graph small without losing real edges
                acct = self._accounts[name] = Account(
                    name, analysis.make_lock("governor.account"))
        return acct

    def total_bytes(self) -> int:
        with self._lock:
            accounts = list(self._accounts.values())
        return sum(a.nbytes for a in accounts)

    def budget_bytes(self) -> int:
        """Current soft budget in bytes (0 = accounting only)."""
        from bigdl_tpu.utils import config
        mb = config.get_float("bigdl.resources.hostMemBudgetMB", 0.0)
        return int(mb * (1 << 20)) if mb > 0 else 0

    def free_bytes(self) -> int:
        """Budget headroom (a large sentinel when no budget is set) —
        the value the chaos injector clamps."""
        budget = self.budget_bytes()
        if budget <= 0:
            return 1 << 62
        return budget - self.total_bytes()

    # ---- budget reaction -----------------------------------------------

    def register_shrinker(self, name: str,
                          fn: Callable[[], None]) -> None:
        """Register a depth-reduction callback (halve a ring, pause
        read-ahead).  Run-scoped: unregister on teardown."""
        with self._lock:
            self._shrinkers[name] = fn

    def unregister_shrinker(self, name: str) -> None:
        with self._lock:
            self._shrinkers.pop(name, None)

    def poll(self) -> bool:
        """One governor tick (called from the driver loop and the ingest
        consumer).  Returns True when a pressure excursion fired the
        shrinkers this tick."""
        from bigdl_tpu.utils import chaos
        with self._lock:
            self._polls += 1
            polls = self._polls
        free = self.free_bytes()
        if chaos.host_mem_pressure(polls):
            free = 0    # injected pressure: reported headroom vanishes
        under = free <= 0
        fired = False
        with self._lock:
            if under and not self._under_pressure:
                fired = True
                self._pressure_events += 1
            self._under_pressure = under
            shrinkers = list(self._shrinkers.items()) if fired else []
        if fired:
            from bigdl_tpu import telemetry
            from bigdl_tpu.telemetry import incident
            telemetry.counter(
                "Resources/host_pressure",
                help="host-memory pressure excursions (budget or "
                     "injected) that fired the shrinkers").inc()
            incident.record("governor/shrink",
                            accounted_bytes=self.total_bytes(),
                            budget_bytes=self.budget_bytes(),
                            shrinkers=[name for name, _ in shrinkers])
            logger.warning(
                "host-memory pressure: %d B accounted vs %d B budget — "
                "shrinking %d registered buffer(s)", self.total_bytes(),
                self.budget_bytes(), len(shrinkers))
            for name, fn in shrinkers:
                try:
                    fn()
                except Exception as e:   # a broken shrinker must not
                    logger.warning(      # take the driver loop down
                        "resource shrinker %r failed: %r", name, e)
        return fired

    def under_pressure(self) -> bool:
        with self._lock:
            return self._under_pressure

    def check_item(self, name: str, nbytes: int) -> None:
        """Escalate when ONE item is larger than the whole budget: depth
        shrinking bottoms out at 1, so no backpressure can save this."""
        budget = self.budget_bytes()
        if budget > 0 and int(nbytes) > budget:
            from bigdl_tpu import telemetry
            telemetry.counter(
                "Resources/host_budget_exceeded",
                help="single-item host-memory budget escalations").inc()
            raise HostMemoryError(name, int(nbytes), budget)

    # ---- telemetry / lifecycle -----------------------------------------

    def summary_scalars(self) -> Iterable[Tuple[str, float]]:
        yield ("Resources/host_bytes", float(self.total_bytes()))
        with self._lock:
            accounts = list(self._accounts.values())
            events = self._pressure_events
        for a in accounts:
            yield (f"Resources/host_bytes_{a.name}", float(a.nbytes))
        yield ("Resources/host_pressure_events", float(events))

    def reset(self) -> None:
        """Drop all accounts/shrinkers/counters (test isolation)."""
        with self._lock:
            self._accounts.clear()
            self._shrinkers.clear()
            self._polls = 0
            self._pressure_events = 0
            self._under_pressure = False


#: the process-wide governor every accounted buffer reports to
GOVERNOR = HostMemoryGovernor()


def item_nbytes(obj, _depth: int = 0) -> int:
    """Best-effort host-byte estimate of one buffered item: numpy/jax
    arrays report ``nbytes``, bytes-likes their length, containers the
    sum of their members (depth-capped — accounting must stay O(item),
    never a deep graph walk)."""
    if obj is None or _depth > 3:
        return 0
    n = getattr(obj, "nbytes", None)
    if n is not None:
        try:
            return int(n)
        except (TypeError, ValueError):
            return 0
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return len(obj)
    if isinstance(obj, str):
        return len(obj)
    if isinstance(obj, dict):
        return sum(item_nbytes(v, _depth + 1) for v in obj.values())
    if isinstance(obj, (tuple, list)):
        return sum(item_nbytes(v, _depth + 1) for v in obj)
    return 0


def _register_provider() -> None:
    from bigdl_tpu import telemetry
    telemetry.REGISTRY.register_provider(
        "resources", GOVERNOR.summary_scalars)


_register_provider()
