"""Device-memory preflight and dispatch-time OOM classification.

The PR 11 HLO census already extracts a peak-buffer estimate from every
compiled fused step (``analysis.hlo_audit.peak_buffer_bytes``).  This
module turns that census into a *gate*: with
``bigdl.resources.deviceMemBudgetMB`` set, ``CachedStep`` calls
:func:`preflight` after compilation and BEFORE the first dispatch — a
step that cannot fit raises :class:`DeviceMemoryError` while the
training state is still untouched, so the driver's microbatch re-plan
starts from exactly the state the oversized step would have consumed.

Dispatch-time failures (a real XLA RESOURCE_EXHAUSTED, or the chaos
injector ``bigdl.chaos.oomStepAt`` replicating its message) are folded
into the same structured error by :func:`classify_dispatch_error`, so
the driver has ONE resource-fault class to re-plan against.
"""

from __future__ import annotations

import logging
from typing import Optional

from bigdl_tpu.resources.errors import DeviceMemoryError, is_oom_error

logger = logging.getLogger("bigdl_tpu")


def budget_bytes() -> int:
    """Configured device-memory budget in bytes (0 = preflight off)."""
    from bigdl_tpu.utils import config
    mb = config.get_float("bigdl.resources.deviceMemBudgetMB", 0.0)
    return int(mb * (1 << 20)) if mb > 0 else 0


def preflight(compiled, label: str) -> Optional[int]:
    """Evaluate a compiled executable's peak-bytes estimate against the
    budget before it ever dispatches.  Returns the peak estimate (None
    when the backend cannot report one — never a false positive), or
    raises :class:`DeviceMemoryError` on a breach."""
    budget = budget_bytes()
    if budget <= 0 or compiled is None:
        return None
    from bigdl_tpu.analysis.hlo_audit import peak_buffer_bytes
    peak = peak_buffer_bytes(compiled)
    if peak is None:
        return None
    from bigdl_tpu import telemetry
    telemetry.gauge("Resources/device_peak_bytes",
                    labels={"step": label},
                    help="preflight peak-buffer estimate per fused step"
                    ).set(peak)
    if peak > budget:
        telemetry.counter(
            "Resources/device_oom",
            help="device-memory faults (preflight breaches + dispatch "
                 "RESOURCE_EXHAUSTED)").inc()
        raise DeviceMemoryError(label, peak, budget, phase="preflight")
    return peak


def preflight_pool(nbytes: int, label: str) -> int:
    """Gate a fixed device-resident pool (the paged KV cache) against the
    same budget the per-step preflight enforces.  Called BEFORE the pool
    buffers are created, so an over-budget pool is a sizing error
    answered while device state is still untouched — never a device OOM
    halfway through serving.  Returns ``nbytes`` (the gate is a
    pass-through when no budget is configured)."""
    from bigdl_tpu import telemetry
    telemetry.gauge("Resources/device_pool_bytes",
                    labels={"pool": label},
                    help="requested bytes per fixed device pool"
                    ).set(nbytes)
    budget = budget_bytes()
    if budget > 0 and nbytes > budget:
        telemetry.counter(
            "Resources/device_oom",
            help="device-memory faults (preflight breaches + dispatch "
                 "RESOURCE_EXHAUSTED)").inc()
        raise DeviceMemoryError(label, nbytes, budget, phase="preflight")
    return int(nbytes)


def classify_dispatch_error(e: BaseException,
                            label: str) -> Optional[DeviceMemoryError]:
    """Fold a dispatch-time allocation failure into the structured
    RESOURCE taxonomy.  Returns the classified error (counted), or None
    when ``e`` is not an OOM (caller re-raises the original)."""
    if isinstance(e, DeviceMemoryError):
        return e
    if not is_oom_error(e):
        return None
    from bigdl_tpu import telemetry
    telemetry.counter(
        "Resources/device_oom",
        help="device-memory faults (preflight breaches + dispatch "
             "RESOURCE_EXHAUSTED)").inc()
    err = DeviceMemoryError(label, None, budget_bytes() or None,
                            phase="dispatch")
    err.__cause__ = e
    return err
