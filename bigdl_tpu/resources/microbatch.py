"""Microbatch re-planning: the driver's answer to device OOM.

When a fused step raises :class:`DeviceMemoryError`, re-running the
same program is pointless — it re-OOMs forever.  The re-plan splits the
global batch of B samples into k equal accumulation chunks: the step
runs k forward/backward passes over B/k samples each and applies ONE
optimizer update with the mean gradient.  Peak activation memory drops
roughly k-fold while the numerics stay allclose to the full-batch step:
the mean of k equal-chunk gradient means IS the full-batch gradient
mean, and the in-scan accumulation uses Kahan compensated summation so
the k-term reduction does not lose low-order bits the single-pass
reduction would have kept.

The helpers here are pure and trace-safe (used INSIDE the jitted step);
the driver-side policy (when to re-plan, how to grow k) is
:func:`next_k` / :func:`snap_k`.
"""

from __future__ import annotations

from typing import Callable, Optional


def snap_k(batch_size: int, k: int) -> int:
    """Smallest divisor of ``batch_size`` that is >= ``k`` — equal-size
    chunks are what makes mean-of-chunk-means equal the full-batch mean
    (and what keeps one compiled chunk signature, not a ragged tail)."""
    b = max(1, int(batch_size))
    k = max(1, min(int(k), b))
    while b % k:
        k += 1
    return k


def next_k(batch_size: int, current_k: int) -> Optional[int]:
    """The re-plan schedule: 1 → 2 → 4 → … (snapped to divisors of the
    batch), until per-sample (k == B) has been tried; then None — the
    model does not fit at microbatch 1 and the fault is fatal."""
    b = max(1, int(batch_size))
    cur = max(1, int(current_k))
    if cur >= b:
        return None
    return snap_k(b, cur * 2)


def chunk_leading(tree, k: int):
    """Reshape every leaf's leading dim B into (k, B // k) — the scan
    axis of the accumulation loop.  Trace-safe."""
    import jax

    def _split(a):
        return a.reshape((k, a.shape[0] // k) + tuple(a.shape[1:]))

    return jax.tree_util.tree_map(_split, tree)


def scan_mean(fn: Callable, xs, k: int):
    """Compensated mean of ``fn`` over ``k`` leading-dim chunks of the
    pytree ``xs`` (every leaf's leading dim divisible by ``k``).

    ``fn(chunk_tree)`` returns a pytree of float arrays; the result is
    the same pytree holding the Kahan-compensated mean over the k
    chunks.  Runs as one ``lax.scan`` so the re-planned step stays a
    single fused program (one signature for the retrace sentinel)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    chunked = chunk_leading(xs, k)
    first = fn(jax.tree_util.tree_map(lambda a: a[0], chunked))
    zeros = jax.tree_util.tree_map(jnp.zeros_like, first)

    def body(carry, chunk):
        acc, comp = carry
        val = fn(chunk)
        # Kahan step per leaf: y = v - comp; t = acc + y;
        # comp = (t - acc) - y; acc = t
        y = jax.tree_util.tree_map(lambda v, c: v - c, val, comp)
        t = jax.tree_util.tree_map(lambda a, yy: a + yy, acc, y)
        comp = jax.tree_util.tree_map(
            lambda tt, a, yy: (tt - a) - yy, t, acc, y)
        return (t, comp), None

    (acc, _), _ = lax.scan(body, (zeros, zeros), chunked)

    def _mean(a):
        # integer leaves (module-state counters) must keep their dtype:
        # equal-per-chunk values floor-divide back exactly, and a float
        # promotion here would drift the carry signature between the
        # full-batch and re-planned programs
        if jnp.issubdtype(a.dtype, jnp.inexact):
            return a / k
        return (a // k).astype(a.dtype)

    return jax.tree_util.tree_map(_mean, acc)
