"""Structured resource-exhaustion errors and their classifiers.

The retry taxonomy in the optimizer driver distinguishes three resource
fault classes, none of which is a divergence and none of which should
burn the retry-from-snapshot budget:

* :class:`DeviceMemoryError` — the fused step does not fit HBM, found
  either by the pre-dispatch preflight (``compiled.memory_analysis()``
  peak vs ``bigdl.resources.deviceMemBudgetMB``) or by a real/injected
  RESOURCE_EXHAUSTED at dispatch.  The driver answers with a microbatch
  re-plan, not a retry: re-running the same program re-OOMs forever.
* :class:`HostMemoryError` — even a depth-1 buffer exceeds
  ``bigdl.resources.hostMemBudgetMB``.  Shrinking cannot help; the run
  escalates immediately with the offending account named.
* :class:`StorageExhaustedError` — ENOSPC/EDQUOT classified at the
  ``file_io.write_bytes`` choke point.  ``fatal = True`` so the
  transient-IO retry refuses to absorb it (re-writing to a full disk
  yields a full disk); callers degrade gracefully instead.

This module stays import-light (stdlib only) so ``utils.file_io`` can
import it without dragging in telemetry or jax.
"""

from __future__ import annotations

import errno
from typing import Optional


class ResourceError(RuntimeError):
    """Base class for the RESOURCE fault taxonomy: exhaustion of device
    memory, host memory, or storage — never a numerics problem, never
    retried against an unchanged plan."""


class DeviceMemoryError(ResourceError):
    """The fused step cannot fit device memory.

    ``phase`` is ``"preflight"`` (caught from ``memory_analysis()``
    before the first dispatch) or ``"dispatch"`` (a real or injected
    RESOURCE_EXHAUSTED surfaced at execution).  The driver's answer is
    a microbatch re-plan — splitting the global batch into k
    gradient-accumulation steps — never a same-plan retry."""

    def __init__(self, label: str, peak_bytes: Optional[int],
                 budget_bytes: Optional[int], phase: str = "dispatch"):
        self.label = label
        self.peak_bytes = peak_bytes
        self.budget_bytes = budget_bytes
        self.phase = phase
        peak = "?" if peak_bytes is None else f"{peak_bytes}"
        budget = "?" if budget_bytes is None else f"{budget_bytes}"
        super().__init__(
            f"device memory exhausted ({phase}) on step {label!r}: "
            f"peak {peak} B vs budget {budget} B — microbatch re-plan "
            "required")


class HostMemoryError(ResourceError):
    """A single buffered item exceeds the host-memory budget: the
    governor's depth shrinking has no move left (depth 1 is already too
    big), so the run escalates with the owning account named."""

    def __init__(self, account: str, nbytes: int, budget_bytes: int):
        self.account = account
        self.nbytes = nbytes
        self.budget_bytes = budget_bytes
        super().__init__(
            f"host memory budget exhausted: one item of {nbytes} B in "
            f"buffer {account!r} exceeds "
            f"bigdl.resources.hostMemBudgetMB ({budget_bytes} B) — "
            "even depth 1 cannot fit; lower the batch/record size or "
            "raise the budget")


class StorageExhaustedError(OSError):
    """ENOSPC/EDQUOT classified at the payload-write choke point.

    ``fatal`` makes ``file_io._is_transient`` refuse to retry it — a
    full disk does not recover on a backoff schedule.  Consumers
    (checkpoint manager, compile cache, telemetry exporters) degrade
    instead of crashing."""

    #: never absorbed by the transient-IO retry
    fatal = True

    def __init__(self, path: str, original: Optional[BaseException] = None):
        self.path = path
        self.original = original
        code = getattr(original, "errno", None) or errno.ENOSPC
        super().__init__(code,
                         f"storage exhausted writing {path} "
                         f"({errno.errorcode.get(code, code)})")


#: substrings that mark an XLA allocation failure — the real runtime
#: raises RuntimeError/XlaRuntimeError whose message leads with the
#: RESOURCE_EXHAUSTED status code; the chaos injector mimics it exactly
#: so one classifier covers both.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory",
                "out of memory", "OOM when allocating")

_STORAGE_ERRNOS = (errno.ENOSPC, errno.EDQUOT)


def is_oom_error(e: BaseException) -> bool:
    """True when ``e`` is a device allocation failure (real XLA
    RESOURCE_EXHAUSTED or the chaos injector's replica of it)."""
    if isinstance(e, DeviceMemoryError):
        return True
    msg = str(e)
    return any(m in msg for m in _OOM_MARKERS)


def is_storage_exhausted(e: BaseException) -> bool:
    """True when ``e`` is a disk-full class error (already classified,
    or a raw OSError carrying ENOSPC/EDQUOT)."""
    if isinstance(e, StorageExhaustedError):
        return True
    return (isinstance(e, OSError) and
            getattr(e, "errno", None) in _STORAGE_ERRNOS)
