"""Disk-full graceful degradation: shared state + guarded exporters.

Once ``file_io.write_bytes`` classifies an ENOSPC/EDQUOT into
:class:`~bigdl_tpu.resources.errors.StorageExhaustedError`, each
consumer degrades instead of crashing and records it here:

* the checkpoint manager drops oldest snapshots beyond ``keep_last``
  and, when the disk still refuses, keeps in-memory-only snapshots;
* the compile cache stops attempting stores and serves from memory
  (the PR 8 lock-loser path, reused);
* telemetry snapshot / Chrome-trace / timeline exports disable
  themselves through :func:`guarded_export`.

Every component degrades with exactly ONE structured warning and one
``Resources/storage_degraded`` counter increment — a full disk on a long
run must not also fill the logs.

This module also owns the timeline-dump bound (the satellite fix): a
flapping slow-step detector or watchdog may dump a timeline per fire,
and an unbounded stream of dump files would fill the very disk the
tentpole is defending.  :func:`bounded_timeline_export` caps files per
run at ``bigdl.telemetry.maxTimelineDumps`` with oldest-first eviction.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Dict, List, Optional

from bigdl_tpu import analysis as _analysis
from bigdl_tpu.resources.errors import (StorageExhaustedError,
                                        is_storage_exhausted)

logger = logging.getLogger("bigdl_tpu")

_lock = _analysis.make_lock("storage.degraded")
_degraded: Dict[str, str] = {}          # component -> first error message
_timeline_dumps: List[str] = []         # dump paths, oldest first


def note_degraded(component: str, error: BaseException) -> bool:
    """Record a component's storage degradation.  Returns True the
    first time (callers log/flag once), False on repeats (silent)."""
    with _lock:
        first = component not in _degraded
        if first:
            _degraded[component] = repr(error)
    if first:
        from bigdl_tpu import telemetry
        telemetry.counter(
            "Resources/storage_degraded", labels={"component": component},
            help="components degraded to diskless operation after "
                 "ENOSPC/EDQUOT").inc()
        logger.warning(
            "storage exhausted: %s degrades to diskless operation "
            "(training/serving continue; fix the disk to re-enable): %r",
            component, error)
    return first


def is_degraded(component: Optional[str] = None) -> bool:
    with _lock:
        if component is None:
            return bool(_degraded)
        return component in _degraded


def degraded_components() -> Dict[str, str]:
    with _lock:
        return dict(_degraded)


def guarded_export(component: str, fn: Callable[[], None]) -> bool:
    """Run a best-effort disk export (telemetry snapshot, Chrome trace,
    timeline dump) unless its component already degraded; a disk-full
    failure inside degrades the component instead of propagating.
    Returns True when the export actually ran and succeeded."""
    if is_degraded(component):
        return False
    try:
        fn()
        return True
    except BaseException as e:
        if is_storage_exhausted(e):
            note_degraded(component, e)
            return False
        raise


def bounded_timeline_export(path: str) -> bool:
    """Export the telemetry Chrome-trace timeline to ``path``, bounded:
    at most ``bigdl.telemetry.maxTimelineDumps`` dump files exist per
    run, evicting the oldest dump first.  Storage exhaustion degrades
    the ``timeline`` component (one warning) instead of raising.
    Returns True when the dump landed."""
    from bigdl_tpu.utils import config
    cap = config.get_int("bigdl.telemetry.maxTimelineDumps", 8)
    if cap <= 0 or is_degraded("timeline"):
        return False
    with _lock:
        while len(_timeline_dumps) >= cap:
            victim = _timeline_dumps.pop(0)
            try:
                if os.path.exists(victim):
                    os.unlink(victim)
            except OSError as e:
                logger.warning("timeline-dump eviction of %s failed: %r",
                               victim, e)

    def _export():
        from bigdl_tpu import telemetry
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        telemetry.export_chrome_trace(path)

    ok = guarded_export("timeline", _export)
    if ok:
        with _lock:
            _timeline_dumps.append(path)
    return ok


def timeline_dump_count() -> int:
    with _lock:
        return len(_timeline_dumps)


def reset() -> None:
    """Clear degradation flags and the dump ledger (test isolation)."""
    with _lock:
        _degraded.clear()
        del _timeline_dumps[:]
