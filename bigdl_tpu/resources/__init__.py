"""Resource-exhaustion resilience (ISSUE 14).

Three exhaustion classes, one structured taxonomy, all chaos-proven:

* **device memory** — HBM preflight on every compiled fused step +
  dispatch-time RESOURCE_EXHAUSTED classification; the driver answers a
  :class:`DeviceMemoryError` with an automatic microbatch re-plan
  (:mod:`bigdl_tpu.resources.microbatch`), never a same-plan retry.
* **host memory** — every bounded ingest/prefetch/serving buffer
  registers byte accounting with the :data:`GOVERNOR`; a soft budget
  shrinks ring depths and pauses read-ahead through the existing
  backpressure machinery; :class:`HostMemoryError` escalates only when
  even depth 1 cannot fit.
* **storage** — ENOSPC/EDQUOT classified at the ``file_io.write_bytes``
  choke point into :class:`StorageExhaustedError`; checkpointing,
  compile-cache stores, and telemetry exports degrade to diskless
  operation (:mod:`bigdl_tpu.resources.storage`) — training and serving
  never crash on a full disk.
"""

from __future__ import annotations

from bigdl_tpu.resources.errors import (DeviceMemoryError, HostMemoryError,
                                        ResourceError,
                                        StorageExhaustedError,
                                        is_oom_error, is_storage_exhausted)
from bigdl_tpu.resources.governor import (GOVERNOR, Account,
                                          HostMemoryGovernor, item_nbytes)
from bigdl_tpu.resources import storage
from bigdl_tpu.resources.storage import (bounded_timeline_export,
                                         guarded_export, note_degraded)

__all__ = [
    "Account", "DeviceMemoryError", "GOVERNOR", "HostMemoryError",
    "HostMemoryGovernor", "ResourceError", "StorageExhaustedError",
    "bounded_timeline_export", "guarded_export", "is_oom_error",
    "is_storage_exhausted", "item_nbytes", "note_degraded", "storage",
]
