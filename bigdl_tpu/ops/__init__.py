"""Functional primitives used by the nn layers.

This package is the TPU replacement for the reference's numeric kernels:
``tensor/DenseTensorMath.scala`` (MKL BLAS/VML dispatch), ``nn/NNPrimitive.scala``
(im2col/col2im/pooling hot loops).  Everything here is a pure jax function that
XLA tiles onto the MXU/VPU — no im2col is ever materialised.
"""

from bigdl_tpu.ops.convolution import (conv2d, conv_transpose2d, conv3d,
                                       temporal_conv1d)
from bigdl_tpu.ops.pooling import (max_pool2d, avg_pool2d, max_pool3d,
                                   avg_pool3d, pool_out_size)
