"""Pooling primitives on ``lax.reduce_window``.

Reference equivalent: the hand-written pooling loops in
``nn/NNPrimitive.scala`` (max-pool fwd/bwd float+double variants).  XLA's
reduce-window (and its built-in select-and-scatter gradient) replaces all of
it; ceil-mode is expressed as extra low-priority padding on the high side.

The 2-D primitives take ``format`` ("NCHW"/"NHWC") and are transpose-free
in both: only the window/stride/pad axis positions move, so the
channels-last path (``nn/layout.py``) pools NHWC maps natively.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax.numpy as jnp
from jax import lax


def _spatial_axes(format: str) -> Tuple[int, int]:
    if format == "NCHW":
        return 2, 3
    if format == "NHWC":
        return 1, 2
    raise ValueError(f"unknown data format {format!r}: "
                     f"expected 'NCHW' or 'NHWC'")


def pool_out_size(in_size: int, k: int, stride: int, pad: int,
                  ceil_mode: bool) -> int:
    if ceil_mode:
        out = int(math.ceil((in_size + 2 * pad - k) / stride)) + 1
    else:
        out = int(math.floor((in_size + 2 * pad - k) / stride)) + 1
    if pad > 0 and (out - 1) * stride >= in_size + pad:
        out -= 1  # torch rule: last window must start inside the padded input
    return out


def _hi_pad(in_size: int, k: int, stride: int, pad: int, ceil_mode: bool) -> int:
    out = pool_out_size(in_size, k, stride, pad, ceil_mode)
    return max(0, (out - 1) * stride + k - in_size - pad)


def max_pool2d(x: jnp.ndarray, kernel: Tuple[int, int],
               stride: Tuple[int, int], padding: Tuple[int, int] = (0, 0),
               ceil_mode: bool = False, format: str = "NCHW") -> jnp.ndarray:
    kh, kw = kernel
    sh, sw = stride
    ph, pw = padding
    h_ax, w_ax = _spatial_axes(format)
    pads = [(0, 0)] * x.ndim
    pads[h_ax] = (ph, _hi_pad(x.shape[h_ax], kh, sh, ph, ceil_mode))
    pads[w_ax] = (pw, _hi_pad(x.shape[w_ax], kw, sw, pw, ceil_mode))
    dims = [1] * x.ndim
    dims[h_ax], dims[w_ax] = kh, kw
    strides = [1] * x.ndim
    strides[h_ax], strides[w_ax] = sh, sw
    # Python-scalar init value: an array init defeats XLA's monoid
    # recognition and breaks linearization under jit(value_and_grad).
    return lax.reduce_window(x, -jnp.inf, lax.max, tuple(dims), tuple(strides),
                             tuple(pads))


def avg_pool2d(x: jnp.ndarray, kernel: Tuple[int, int],
               stride: Tuple[int, int], padding: Tuple[int, int] = (0, 0),
               ceil_mode: bool = False, count_include_pad: bool = True,
               format: str = "NCHW") -> jnp.ndarray:
    kh, kw = kernel
    sh, sw = stride
    ph, pw = padding
    h_ax, w_ax = _spatial_axes(format)
    pads = [(0, 0)] * x.ndim
    pads[h_ax] = (ph, _hi_pad(x.shape[h_ax], kh, sh, ph, ceil_mode))
    pads[w_ax] = (pw, _hi_pad(x.shape[w_ax], kw, sw, pw, ceil_mode))
    dims = [1] * x.ndim
    dims[h_ax], dims[w_ax] = kh, kw
    strides = [1] * x.ndim
    strides[h_ax], strides[w_ax] = sh, sw
    summed = lax.reduce_window(x, 0.0, lax.add,
                               tuple(dims), tuple(strides), tuple(pads))
    if count_include_pad:
        return summed / (kh * kw)
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(ones, 0.0, lax.add,
                               tuple(dims), tuple(strides), tuple(pads))
    return summed / counts


def max_pool3d(x: jnp.ndarray, kernel, stride, padding=(0, 0, 0),
               ceil_mode: bool = False) -> jnp.ndarray:
    """NCDHW max pooling (reference ``nn/VolumetricMaxPooling``)."""
    pads = [(0, 0), (0, 0)] + [
        (p, _hi_pad(x.shape[2 + i], kernel[i], stride[i], p, ceil_mode))
        for i, p in enumerate(padding)]
    dims = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, tuple(pads))


def avg_pool3d(x: jnp.ndarray, kernel, stride, padding=(0, 0, 0),
               ceil_mode: bool = False, count_include_pad: bool = True) -> jnp.ndarray:
    pads = [(0, 0), (0, 0)] + [
        (p, _hi_pad(x.shape[2 + i], kernel[i], stride[i], p, ceil_mode))
        for i, p in enumerate(padding)]
    dims = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    summed = lax.reduce_window(x, 0.0, lax.add, dims,
                               strides, tuple(pads))
    if count_include_pad:
        return summed / float(np_prod(kernel))
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(ones, 0.0, lax.add, dims,
                               strides, tuple(pads))
    return summed / counts


def np_prod(xs) -> int:
    out = 1
    for v in xs:
        out *= int(v)
    return out
