"""Non-maximum suppression for object detection.

Reference equivalent: ``nn/Nms.scala`` — sort by score, greedily keep the
highest-scoring box and suppress boxes whose IoU with a kept box exceeds the
threshold.

TPU-first form: a fixed-shape ``lax.fori_loop`` over the score-sorted boxes
producing a suppression mask — no data-dependent shapes, so it compiles
under jit (the host-side ``Nms`` shell then extracts indices, mirroring the
reference's buffer-filling API).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax


def _pairwise_iou(boxes: jnp.ndarray) -> jnp.ndarray:
    """(N, 4) xyxy boxes → (N, N) IoU (torch-style +1 extents, matching the
    reference's ``getAreas``)."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = (x2 - x1 + 1.0) * (y2 - y1 + 1.0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    iw = jnp.maximum(ix2 - ix1 + 1.0, 0.0)
    ih = jnp.maximum(iy2 - iy1 + 1.0, 0.0)
    inter = iw * ih
    return inter / (areas[:, None] + areas[None, :] - inter)


def nms_mask(boxes: jnp.ndarray, scores: jnp.ndarray,
             iou_threshold: float) -> jnp.ndarray:
    """Jit-friendly core: (N, 4) boxes + (N,) scores → (N,) bool keep mask
    (in ORIGINAL box order)."""
    n = boxes.shape[0]
    if n == 0:
        return jnp.zeros((0,), bool)
    order = jnp.argsort(-scores)
    iou = _pairwise_iou(boxes[order])
    idx = jnp.arange(n)

    def body(i, suppressed):
        overlaps = (iou[i] > iou_threshold) & (idx > i)
        new = suppressed | overlaps
        # a suppressed anchor suppresses nothing
        return jnp.where(suppressed[i], suppressed, new)

    suppressed = lax.fori_loop(0, n, body, jnp.zeros((n,), bool))
    keep_sorted = ~suppressed
    return jnp.zeros((n,), bool).at[order].set(keep_sorted)


class Nms:
    """Host-side shell with the reference's call shape
    (``Nms.nms(scores, boxes, thresh, indices) -> count``): returns kept
    indices in descending-score order."""

    def nms(self, scores, boxes, thresh: float,
            indices: Optional[np.ndarray] = None) -> int:
        scores = jnp.asarray(scores).reshape(-1)
        boxes = jnp.asarray(boxes).reshape(-1, 4)
        keep = np.asarray(nms_mask(boxes, scores, thresh))
        order = np.argsort(-np.asarray(scores), kind="stable")
        kept = [int(i) for i in order if keep[i]]
        if indices is not None:
            indices[:len(kept)] = kept
        self.last_indices = np.asarray(kept, dtype=np.int64)
        return len(kept)
