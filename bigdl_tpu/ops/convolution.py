"""Convolution primitives on ``lax.conv_general_dilated``.

Reference equivalent: the im2col + MKL gemm pipeline
(``nn/SpatialConvolution.scala:128-230`` → ``nn/NNPrimitive.scala:108`` →
``tensor/DenseTensorBLAS.scala:70``).  On TPU the XLA convolution emitter owns
the MXU tiling, so there is no materialised im2col buffer and no per-frame
thread pool; we only describe layouts via ``dimension_numbers``.

Kernel storage layout is always HWIO ((kh, kw, in/groups, out)) — the
TPU-friendly layout — independent of the activations' data format.

Data format: every 2-D primitive takes ``format`` ("NCHW"/"NHWC") and is
TRANSPOSE-FREE in NHWC — the TPU-native channels-last layout the model
zoo's interior computes in (``nn/layout.py``); only the NCHW small-taps
matmul path below materialises transposes, and only because channel-first
slicing would defeat the layout anyway.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

_DN = {
    "NCHW": ("NCHW", "HWIO", "NCHW"),
    "NHWC": ("NHWC", "HWIO", "NHWC"),
}


def _dimension_numbers(x_shape, w_shape, format: str):
    if format not in _DN:
        raise ValueError(f"unknown data format {format!r}: "
                         f"expected one of {sorted(_DN)}")
    return lax.conv_dimension_numbers(x_shape, w_shape, _DN[format])


def _same_pad(in_size: int, k: int, s: int, d: int = 1) -> Tuple[int, int]:
    eff_k = (k - 1) * d + 1
    out = -(-in_size // s)
    pad = max(0, (out - 1) * s + eff_k - in_size)
    return pad // 2, pad - pad // 2


# C_in * kh * kw at or below this goes through the slice-stack matmul
# path: XLA's conv WEIGHT-gradient for tiny input channel counts compiles
# pathologically on this backend (LeNet's 1->6 5x5 conv at batch 512:
# >11 min for the conv alone; the same gradient via stacked shifted
# slices + one matmul: 8.7 s, bit-identical forward).  Tiny-channel convs
# are degenerate on the MXU anyway, so the matmul form is also the
# faster runtime layout.
_IM2COL_MAX_TAPS = 32


def _conv2d_smallk(x, weight, stride, pad_hw, format):
    """VALID-after-padding conv as stacked shifted slices + one matmul
    (the reference's im2col+gemm, ``nn/NNPrimitive.scala:108`` — here as
    a compile-time workaround, not a runtime buffer)."""
    kh, kw, c_in, c_out = weight.shape
    if format == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))          # -> NHWC
    x = jnp.pad(x, ((0, 0), pad_hw[0], pad_hw[1], (0, 0)))
    n, h, w, _ = x.shape
    sh, sw = stride
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    cols = jnp.stack(
        [x[:, dy:dy + (oh - 1) * sh + 1:sh,
           dx:dx + (ow - 1) * sw + 1:sw, :]
         for dy in range(kh) for dx in range(kw)], axis=3)  # (N,oh,ow,taps,C)
    cols = cols.reshape(n, oh, ow, kh * kw * c_in)
    # taps-major (dy, dx, c) must match the kernel flatten order
    wmat = weight.reshape(kh * kw * c_in, c_out)
    out = cols @ wmat                                # (N, oh, ow, C_out)
    if format == "NCHW":
        out = jnp.transpose(out, (0, 3, 1, 2))
    return out


def conv2d(x: jnp.ndarray, weight: jnp.ndarray,
           bias: Optional[jnp.ndarray] = None,
           stride: Tuple[int, int] = (1, 1),
           padding: Union[str, Tuple[int, int]] = (0, 0),
           dilation: Tuple[int, int] = (1, 1),
           groups: int = 1,
           format: str = "NCHW") -> jnp.ndarray:
    """2-D convolution (cross-correlation, torch semantics).

    padding: (padH, padW) explicit or "SAME".  BigDL encodes same-padding as
    pad = -1 (``nn/SpatialConvolution.scala``); callers translate that here.
    """
    dn = _dimension_numbers(x.shape, weight.shape, format)
    if padding == "SAME":
        h_ax, w_ax = (2, 3) if format == "NCHW" else (1, 2)
        pad = (_same_pad(x.shape[h_ax], weight.shape[0], stride[0], dilation[0]),
               _same_pad(x.shape[w_ax], weight.shape[1], stride[1], dilation[1]))
    else:
        pad = ((padding[0], padding[0]), (padding[1], padding[1]))
    kh, kw, c_in_g, _ = weight.shape
    if (groups == 1 and dilation == (1, 1) and
            kh * kw * c_in_g <= _IM2COL_MAX_TAPS):
        out = _conv2d_smallk(x, weight, stride, pad, format)
    else:
        out = lax.conv_general_dilated(
            x, weight, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
    if bias is not None:
        bshape = (1, -1, 1, 1) if format == "NCHW" else (1, 1, 1, -1)
        out = out + jnp.reshape(bias, bshape)
    return out


def conv_transpose2d(x: jnp.ndarray, weight: jnp.ndarray,
                     bias: Optional[jnp.ndarray] = None,
                     stride: Tuple[int, int] = (1, 1),
                     padding: Tuple[int, int] = (0, 0),
                     adj: Tuple[int, int] = (0, 0),
                     format: str = "NCHW") -> jnp.ndarray:
    """Transposed convolution (reference ``nn/SpatialFullConvolution``).

    weight layout HWIO with I = input planes, O = output planes.
    out = (in - 1) * stride - 2 * pad + kernel + adj.
    """
    kh, kw = weight.shape[0], weight.shape[1]
    dn = _dimension_numbers(x.shape, weight.shape, format)
    pad = ((kh - 1 - padding[0], kh - 1 - padding[0] + adj[0]),
           (kw - 1 - padding[1], kw - 1 - padding[1] + adj[1]))
    # lhs_dilation inserts (stride-1) zeros between input rows/cols: the
    # fractionally-strided view of deconvolution.  The HWIO kernel already has
    # I = this layer's input planes, so only a spatial flip is needed.
    w = jnp.flip(weight, axis=(0, 1))
    out = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pad,
        lhs_dilation=stride, dimension_numbers=dn)
    if bias is not None:
        bshape = (1, -1, 1, 1) if format == "NCHW" else (1, 1, 1, -1)
        out = out + jnp.reshape(bias, bshape)
    return out


def conv3d(x: jnp.ndarray, weight: jnp.ndarray,
           bias: Optional[jnp.ndarray] = None,
           stride: Tuple[int, int, int] = (1, 1, 1),
           padding: Tuple[int, int, int] = (0, 0, 0)) -> jnp.ndarray:
    """3-D convolution, NCDHW activations, DHWIO kernel
    (reference ``nn/VolumetricConvolution``)."""
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    ("NCDHW", "DHWIO", "NCDHW"))
    pad = tuple((p, p) for p in padding)
    out = lax.conv_general_dilated(x, weight, window_strides=stride,
                                   padding=pad, dimension_numbers=dn)
    if bias is not None:
        out = out + jnp.reshape(bias, (1, -1, 1, 1, 1))
    return out


def conv_transpose3d(x: jnp.ndarray, weight: jnp.ndarray,
                     bias: Optional[jnp.ndarray] = None,
                     stride=(1, 1, 1), padding=(0, 0, 0),
                     adj=(0, 0, 0)) -> jnp.ndarray:
    """Transposed 3-D convolution (reference ``nn/VolumetricFullConvolution``)."""
    kd, kh, kw = weight.shape[0], weight.shape[1], weight.shape[2]
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    ("NCDHW", "DHWIO", "NCDHW"))
    ks = (kd, kh, kw)
    pad = tuple((k - 1 - p, k - 1 - p + a) for k, p, a in zip(ks, padding, adj))
    w = jnp.flip(weight, axis=(0, 1, 2))
    out = lax.conv_general_dilated(x, w, window_strides=(1, 1, 1), padding=pad,
                                   lhs_dilation=stride, dimension_numbers=dn)
    if bias is not None:
        out = out + jnp.reshape(bias, (1, -1, 1, 1, 1))
    return out


def temporal_conv1d(x: jnp.ndarray, weight: jnp.ndarray,
                    bias: Optional[jnp.ndarray] = None,
                    stride: int = 1) -> jnp.ndarray:
    """1-D (temporal) convolution (reference ``nn/TemporalConvolution.scala:49``).

    x: (N, T, inputFrameSize); weight: (kw, inputFrameSize, outputFrameSize).
    """
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    ("NWC", "WIO", "NWC"))
    out = lax.conv_general_dilated(x, weight, window_strides=(stride,),
                                   padding=((0, 0),), dimension_numbers=dn)
    if bias is not None:
        out = out + jnp.reshape(bias, (1, 1, -1))
    return out
