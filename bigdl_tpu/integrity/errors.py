"""Structured errors for the training-state integrity subsystem.

The taxonomy mirrors how each fault heals:

- :class:`IntegrityError` — finite-but-wrong state detected on ONE
  logical copy (a continuity break between consecutive fused steps, or a
  checkpoint whose bytes verify but whose semantic fingerprint doesn't).
  The retry loop classifies it like divergence: restore an older valid
  snapshot, never retry in place, and never reset the retry budget on
  the evalCounter ground the frozen run appears to have covered.
- :class:`ReplicaDesyncError` — data-parallel replicas disagree on the
  bitwise parameter fingerprint.  The agreeing majority still holds
  canonical state, so the trainer heals WITHOUT a checkpoint restore:
  re-broadcast the majority's parameters and re-place the ZeRO-1 slots
  (``elastic.place_slots``), then replay from the first desynced
  iteration.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence


class IntegrityError(RuntimeError):
    """Training state failed an integrity check while every value stayed
    finite — silent data corruption, not divergence.  ``iteration`` is
    the first iteration the corruption was observed at (the fused step
    records it on-device, so a delayed driver pull still names the true
    onset)."""

    def __init__(self, message: str, iteration: Optional[int] = None):
        super().__init__(message)
        self.iteration = iteration


class ReplicaDesyncError(IntegrityError):
    """Data-parallel replicas disagree on the parameter fingerprint.

    ``replicas`` names the minority (disagreeing) replica indices,
    ``fingerprints`` carries the full gathered per-replica fingerprint
    table the verdict was computed from, and ``iteration`` the first
    iteration the disagreement was observed on-device."""

    def __init__(self, message: str, replicas: Sequence[int] = (),
                 iteration: Optional[int] = None,
                 fingerprints: Any = None):
        super().__init__(message, iteration)
        self.replicas = tuple(int(r) for r in replicas)
        self.fingerprints = fingerprints
