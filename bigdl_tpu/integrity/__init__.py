"""Training-state integrity: fingerprints, agreement, self-healing.

Detects finite-but-wrong training state — the silent corruption class
every loud-failure guard (divergence, torn checkpoints, hangs) misses —
and heals it through the existing restore/re-placement machinery.  See
``docs/programming-guide/optimization.md`` ("Training-state integrity").
"""

from bigdl_tpu.integrity.errors import IntegrityError, ReplicaDesyncError
from bigdl_tpu.integrity.fingerprint import (
    DEFAULT_SEED,
    GRAD_SEED_OFF,
    NF_SENTINEL,
    SLOT_SEED_OFF,
    acc_dtype,
    continuity_check,
    fingerprint_flat,
    fingerprint_key,
    fingerprint_tree,
    first_nonfinite,
    host_fingerprint,
    init_carry,
    nonfinite_names,
    pack_carry,
    sq_norm,
    sq_norm_diff,
)
from bigdl_tpu.integrity.health import WeightHealthMonitor
from bigdl_tpu.integrity.monitor import (
    DriverIntegrity,
    bitflip_one_replica,
    bitflip_tree,
    majority_split,
    replicated_shard_disagreement,
)

__all__ = [
    "IntegrityError",
    "ReplicaDesyncError",
    "DEFAULT_SEED",
    "NF_SENTINEL",
    "acc_dtype",
    "fingerprint_flat",
    "fingerprint_key",
    "fingerprint_tree",
    "first_nonfinite",
    "host_fingerprint",
    "nonfinite_names",
    "GRAD_SEED_OFF",
    "SLOT_SEED_OFF",
    "continuity_check",
    "init_carry",
    "pack_carry",
    "sq_norm",
    "sq_norm_diff",
    "WeightHealthMonitor",
    "DriverIntegrity",
    "bitflip_one_replica",
    "bitflip_tree",
    "majority_split",
    "replicated_shard_disagreement",
]
