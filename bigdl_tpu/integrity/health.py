"""Weight-health monitor: EMA anomaly gates over integrity series.

Grad-norm and update-ratio trends are the earliest observable symptoms
of a run that is still finite but already exploding — they cross their
healthy band many iterations before the first NaN reaches the
divergence guard.  Each series feeds a
:class:`~bigdl_tpu.telemetry.step_stats.SlowStepDetector` (the exact
anomaly-gate discipline the slow-step and hung-dispatch watchdogs use:
EMA seeded from the warmup MINIMUM so early optimizer transients cannot
poison the baseline, one fire per anomaly window with a cooldown,
``factor <= 0`` disables, anomalies never drag the EMA up).  A fire is
a FLAG, not a fault: it logs, bumps ``Integrity/health_anomalies``, and
leaves the run alone — the operator (or an outer controller) decides
whether a hot trajectory warrants a rollback.
"""

from __future__ import annotations

import logging
import math
from typing import Dict

from bigdl_tpu import telemetry

logger = logging.getLogger("bigdl_tpu")


class WeightHealthMonitor:
    """One anomaly gate per named series (``grad_norm``,
    ``update_ratio``, per-bucket ratios, ...), created lazily so the
    bucket count need not be known up front."""

    def __init__(self, factor: float, warmup: int = 5, cooldown: int = 50):
        self.factor = float(factor)
        self.warmup = int(warmup)
        self.cooldown = int(cooldown)
        self._gates: Dict[str, telemetry.SlowStepDetector] = {}
        self.anomalies = 0

    @property
    def enabled(self) -> bool:
        return self.factor > 0

    def observe(self, series: str, value: float, iteration: int) -> bool:
        """Feed one observation; True iff it opened a new anomaly
        window.  Non-finite values are ignored — the divergence guard
        owns those, and a NaN must not poison the healthy-regime EMA."""
        if not self.enabled or not math.isfinite(value):
            return False
        gate = self._gates.get(series)
        if gate is None:
            gate = telemetry.SlowStepDetector(
                self.factor, warmup=self.warmup, cooldown=self.cooldown)
            self._gates[series] = gate
        fired = gate.observe(value)
        if fired:
            self.anomalies += 1
            telemetry.counter(
                "Integrity/health_anomalies",
                help="weight-health EMA gates fired (finite but "
                     "exploding state)").inc()
            logger.warning(
                "Weight-health anomaly at iteration %d: %s = %.3e "
                "(> %.1fx the healthy EMA %.3e) — state is finite but "
                "trending away from its baseline; a divergence guard "
                "fire may follow", iteration, series, value, self.factor,
                gate.ema if gate.ema else float("nan"))
        return fired
