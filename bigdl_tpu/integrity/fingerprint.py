"""Deterministic training-state fingerprints, on device and on host.

A fingerprint is a ``[compensated sum, random projection]`` pair over
every float element of a tree: the sum catches gross value corruption,
the seed-fixed ±1 (Rademacher) projection catches compensating or
permuting corruptions the plain sum cancels.  Both reduce on device in
the accumulator dtype (f64 when x64 is enabled; f32 otherwise — the
strict HLO precision audit flags ANY f64 op and tier-1 runs x64-off),
and no host pull happens here: the driver reads fingerprints only
through the explicit ``analysis.host_pull`` choke point.

The projection signs are NOT an embedded constant table: they are
recomputed from ``iota`` with a multiplicative xorshift hash (~5 integer
ops per element), pure in ``(position, seed)``, so the traced program
stays O(1) in parameter count and the host mirror
(:func:`host_fingerprint`) reproduces the identical sign stream with
numpy.  Host and device fingerprints are each SELF-consistent (same
algorithm, same seed ⇒ same value for the same bits) but are never
compared to each other — summation order differs across backends.

Also here: :func:`first_nonfinite`, the diagnosed flavor of the
divergence guard's ``all_finite`` — same per-leaf reductions, plus an
int32 index of the first non-finite leaf so the driver's log line and
``DivergenceError`` can name the tree and leaf path that went bad.
"""

from __future__ import annotations

import struct
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: default projection seed (``bigdl.integrity.seed``)
DEFAULT_SEED = 0x51D0
#: ``first_nonfinite`` index when every leaf is finite
NF_SENTINEL = 2 ** 31 - 1

# Knuth / xxhash-style avalanche constants for the sign stream
_MIX1 = np.uint32(2654435761)
_MIX2 = np.uint32(2246822519)
_MIX3 = np.uint32(3266489917)
#: per-leaf seed stride (golden-ratio odd constant)
_LEAF_STRIDE = 0x9E3779B9


def acc_dtype():
    """Fingerprint accumulator dtype: f64 under x64, f32 otherwise."""
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _device_signs(n: int, seed: int):
    """±1 signs for positions 0..n-1, pure in ``(n, seed)``."""
    i = jax.lax.iota(jnp.uint32, n)
    x = (i * _MIX1) ^ np.uint32(seed & 0xFFFFFFFF)
    x = (x ^ (x >> 15)) * _MIX2
    x = (x ^ (x >> 13)) * _MIX3
    x = x ^ (x >> 16)
    return 1.0 - 2.0 * (x >> 31).astype(acc_dtype())


def _host_signs(n: int, seed: int) -> np.ndarray:
    """Numpy mirror of :func:`_device_signs` — bit-identical stream."""
    i = np.arange(n, dtype=np.uint32)
    with np.errstate(over="ignore"):
        x = (i * _MIX1) ^ np.uint32(seed & 0xFFFFFFFF)
        x = (x ^ (x >> np.uint32(15))) * _MIX2
        x = (x ^ (x >> np.uint32(13))) * _MIX3
        x = x ^ (x >> np.uint32(16))
    return 1.0 - 2.0 * (x >> np.uint32(31)).astype(np.float64)


def fingerprint_flat(vec, seed: int):
    """``(2,)`` ``[sum, projection]`` of one flat float vector, in the
    accumulator dtype.  Zero padding contributes exactly zero to both
    components, so padded flat parameter vectors fingerprint their
    payload.

    The reductions run behind an ``optimization_barrier``: continuity
    compares a value fingerprinted at the END of step k (where the
    producer may be a concatenate/all-gather XLA would happily fuse the
    reduce into, reassociating the float sum) against the SAME bits
    fingerprinted at the START of step k+1 (a plain program input).
    Bitwise equality needs both sites to reduce a materialized vector
    with the identical loop structure, so the barrier pins the operand
    and keeps producer fusion out of the sum."""
    acc = acc_dtype()
    v = jax.lax.optimization_barrier(jnp.asarray(vec).astype(acc))
    # the value keeps its native shape (and, under GSPMD, its sharding
    # — ravelling a tensor-parallel leaf would force the partitioner to
    # rematerialize the PARAMETER); the generated sign stream reshapes
    # to match instead, which costs a per-shard iota at worst
    signs = _device_signs(v.size, seed).reshape(v.shape)
    return jnp.stack([jnp.sum(v), jnp.sum(v * signs)])


def fingerprint_tree(tree, seed: int):
    """``(2,)`` fingerprint over every float leaf of a pytree; each leaf
    draws its own sign stream (seed advances by a golden-ratio stride
    per leaf) so swapping values between leaves changes the projection."""
    acc = acc_dtype()
    total = jnp.zeros((2,), acc)
    idx = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        leaf = jnp.asarray(leaf)
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            idx += 1
            total = total + fingerprint_flat(leaf, seed + _LEAF_STRIDE * idx)
    return total


#: seed offsets separating the three fingerprinted trees — params,
#: optimizer slots, gradients each draw disjoint sign streams
SLOT_SEED_OFF = 0x5D3F9B31
GRAD_SEED_OFF = 0x2B7E1516


def init_carry() -> np.ndarray:
    """Fresh host-side integrity carry: ``[seen, latch, bad_iter,
    p_sum, p_proj, s_sum, s_proj]`` — all zero (``seen == 0`` makes the
    first step record instead of compare).  Reset after every heal or
    restore: the new state legitimately mismatches the old carry."""
    import jax as _jax
    dt = np.float64 if _jax.config.jax_enable_x64 else np.float32
    return np.zeros((7,), dt)


def continuity_check(fpc, fp_p_in, fp_s_in, tick, extra_ok=None):
    """In-step continuity verdict against the carry from the previous
    step: ``(cont_ok, latch, bad_iter)``.  ``cont_ok`` is False when the
    input params/slots fingerprints mismatch what the previous step
    wrote out (state changed OUTSIDE the fused step — silent in-memory
    corruption); ``latch`` is sticky (a one-step corruption must survive
    until the driver's next cadence pull); ``bad_iter`` records the
    FIRST bad tick so the heal can rewind to the exact onset.

    ``extra_ok`` folds an additional verdict into the latch — the
    shard_map family passes its cross-replica agreement verdict so a
    copy divergence latches with the same first-bad-tick bookkeeping.
    Unlike the continuity match, it applies even on the first step
    (``seen == 0``): disagreeing copies are corrupt regardless of
    whether a carry exists yet."""
    acc = acc_dtype()
    seen = fpc[0]
    match = ((fp_p_in[0] == fpc[3]) & (fp_p_in[1] == fpc[4]) &
             (fp_s_in[0] == fpc[5]) & (fp_s_in[1] == fpc[6]))
    cont_ok = jnp.logical_or(seen == 0, match)
    if extra_ok is not None:
        cont_ok = jnp.logical_and(cont_ok, extra_ok)
    latch = jnp.maximum(
        fpc[1], jnp.where(cont_ok, jnp.zeros((), acc), jnp.ones((), acc)))
    first_bad = jnp.logical_and(jnp.logical_not(cont_ok), fpc[2] == 0)
    bad_iter = jnp.where(first_bad, tick.astype(acc), fpc[2])
    return cont_ok, latch, bad_iter


def pack_carry(latch, bad_iter, fp_p_out, fp_s_out):
    """The (7,) carry for the next step, from this step's verdicts and
    OUTPUT fingerprints."""
    acc = acc_dtype()
    return jnp.stack([jnp.ones((), acc), latch, bad_iter,
                      fp_p_out[0], fp_p_out[1],
                      fp_s_out[0], fp_s_out[1]])


def sq_norm(tree):
    """Sum of squares over every float leaf (accumulator dtype) — the
    weight-health monitor's param/grad norm source."""
    acc = acc_dtype()
    total = jnp.zeros((), acc)
    for leaf in jax.tree_util.tree_leaves(tree):
        leaf = jnp.asarray(leaf)
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            v = leaf.astype(acc)
            total = total + jnp.sum(v * v)
    return total


def sq_norm_diff(new_tree, old_tree):
    """Sum of squared per-element differences over float leaves — the
    applied-update norm (zero when the guard froze the step)."""
    acc = acc_dtype()
    total = jnp.zeros((), acc)
    new_leaves = jax.tree_util.tree_leaves(new_tree)
    old_leaves = jax.tree_util.tree_leaves(old_tree)
    for n, o in zip(new_leaves, old_leaves):
        n = jnp.asarray(n)
        if jnp.issubdtype(n.dtype, jnp.floating):
            d = n.astype(acc) - jnp.asarray(o).astype(acc)
            total = total + jnp.sum(d * d)
    return total


def fingerprint_key(fp) -> str:
    """Bitwise-exact comparison key for a ``[sum, proj]`` pair: the hex
    of the two IEEE-754 doubles.  NaN-safe (NaN != NaN under ``==`` but
    its bytes compare equal) and sign-of-zero exact."""
    a = np.asarray(fp, dtype=np.float64).ravel()
    return struct.pack("<2d", float(a[0]), float(a[1])).hex()


def _is_float_array(x) -> bool:
    dt = getattr(x, "dtype", None)
    if dt is None:
        return False
    return getattr(dt, "kind", "") == "f" or str(dt) in (
        "bfloat16", "float16")


def _collect_float_leaves(obj, out: List[np.ndarray], seen: set) -> None:
    """Deterministic walk collecting float arrays/scalars from an
    arbitrary picklable object graph — dicts/lists/tuples in order,
    objects via ``__dict__`` (both orders survive a pickle round-trip),
    cycle-guarded by id."""
    if obj is None or isinstance(obj, (str, bytes, bool, int)):
        return
    if isinstance(obj, float):
        out.append(np.asarray([obj], dtype=np.float64))
        return
    if _is_float_array(obj):
        out.append(np.asarray(obj, dtype=np.float64))
        return
    if hasattr(obj, "dtype"):
        return  # non-float array (int buffers, rng keys)
    oid = id(obj)
    if oid in seen:
        return
    if isinstance(obj, dict):
        seen.add(oid)
        for v in obj.values():
            _collect_float_leaves(v, out, seen)
        return
    if isinstance(obj, (list, tuple)):
        seen.add(oid)
        for v in obj:
            _collect_float_leaves(v, out, seen)
        return
    d = getattr(obj, "__dict__", None)
    if isinstance(d, dict):
        seen.add(oid)
        for v in d.values():
            _collect_float_leaves(v, out, seen)


def host_fingerprint(obj, seed: int = DEFAULT_SEED) -> List[float]:
    """Host-side ``[sum, projection]`` (python floats, f64 accumulation)
    over every float leaf reachable from ``obj`` — the semantic
    checkpoint fingerprint.  Computed on the live object before
    serialization and recomputed on the unpickled object at restore;
    identical values ⇒ identical fingerprint, so corruption between
    compute and serialization (which payload checksums can NOT see — the
    CRC is taken over the already-corrupt bytes) surfaces as a mismatch."""
    leaves: List[np.ndarray] = []
    _collect_float_leaves(obj, leaves, set())
    s = 0.0
    p = 0.0
    for idx, arr in enumerate(leaves):
        v = arr.ravel()
        signs = _host_signs(v.size, seed + _LEAF_STRIDE * (idx + 1))
        s += float(v.sum())
        p += float(v.dot(signs))
    return [s, p]


def first_nonfinite(*trees) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``(ok, idx)``: ``ok`` is exactly ``all_finite(*trees)``; ``idx``
    is the int32 position (float-leaf order across the given trees) of
    the FIRST leaf containing a non-finite value, or :data:`NF_SENTINEL`
    when everything is finite.  Same reduction budget as ``all_finite``
    plus one scalar min-chain — cheap enough to stay always-on under the
    divergence guard."""
    sentinel = np.int32(NF_SENTINEL)
    idx = jnp.asarray(sentinel)
    j = 0
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            leaf = jnp.asarray(leaf)
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                bad = jnp.logical_not(jnp.all(jnp.isfinite(leaf)))
                idx = jnp.minimum(
                    idx, jnp.where(bad, np.int32(j), sentinel))
                j += 1
    return idx == sentinel, idx


def nonfinite_names(*labeled_trees) -> List[str]:
    """Build-time name table matching :func:`first_nonfinite`'s index
    space: ``labeled_trees`` is ``(label, template_tree)`` pairs in the
    same order the trees are passed to ``first_nonfinite``; float leaves
    get ``label:<key path>`` names (bare ``label`` for a scalar)."""
    names: List[str] = []
    for label, tree in labeled_trees:
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        for path, leaf in flat:
            dt = getattr(leaf, "dtype", None)
            if dt is not None and not jnp.issubdtype(dt, jnp.floating):
                continue
            if dt is None and not isinstance(leaf, float):
                continue
            try:
                key = jax.tree_util.keystr(path)
            except Exception:  # pragma: no cover - older jax
                key = "".join(str(p) for p in path)
            names.append(f"{label}:{key}" if key else str(label))
    return names
