"""Driver-side integrity companion for the fused-step families.

The fused steps compute fingerprints / agreement verdicts EVERY
iteration on device (and AND them into the update-skip guard, so a
corrupted replica can never contaminate healthy state — the run freezes
instead); the driver pulls the small aux tree through the
``analysis.host_pull`` choke point every ``bigdl.integrity.everyN``
iterations and hands it here.  :meth:`DriverIntegrity.check` classifies
the pulled verdicts — cross-replica disagreement raises
:class:`~bigdl_tpu.integrity.errors.ReplicaDesyncError` naming the
minority replicas, a continuity break raises
:class:`~bigdl_tpu.integrity.errors.IntegrityError` — and feeds the
weight-health EMA gates plus the ``Integrity/*`` registry metrics.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu import telemetry
from bigdl_tpu.integrity.errors import IntegrityError, ReplicaDesyncError
from bigdl_tpu.integrity.fingerprint import NF_SENTINEL
from bigdl_tpu.integrity.health import WeightHealthMonitor

logger = logging.getLogger("bigdl_tpu")


def majority_split(keys: Sequence[bytes]):
    """``(majority_key, minority_indices)`` of a list of bitwise
    fingerprint keys.  Ties break toward the key holding the
    lowest-indexed replica — with half the fleet corrupted there is no
    canonical side, and a deterministic pick beats a coin flip."""
    counts: Dict[bytes, int] = {}
    for k in keys:
        counts[k] = counts.get(k, 0) + 1
    best = max(counts.items(), key=lambda kv: (kv[1], -keys.index(kv[0])))
    major = best[0]
    minority = [i for i, k in enumerate(keys) if k != major]
    return major, minority


def replicated_shard_disagreement(arr, what: str = "integrity replica "
                                                   "shard"):
    """Bitwise-compare the per-device copies of a REPLICATED array
    (driver-side agreement for the GSPMD family, where the traced
    program is collective-free and replication is the partitioner's
    promise): returns ``(minority_replica_indices, per_copy_bytes)``.
    Pulls go through the explicit host choke point."""
    from bigdl_tpu.analysis.hostsync import host_pull
    shards = sorted(arr.addressable_shards, key=lambda s: s.device.id)
    keys = [np.asarray(host_pull(s.data, what=what)).tobytes()
            for s in shards]
    _, minority = majority_split(keys)
    return minority, keys


def _flip_low_bit(host: np.ndarray) -> np.ndarray:
    """Flip one mid-mantissa bit of the first element — finite-preserving
    corruption invisible to ``all_finite`` and far below loss-curve
    resolution, but ABOVE the fingerprint's detection floor: the
    fingerprint reduces in the accumulation dtype (f32), so a 1-ULP flip
    can round away against the running sum; the chosen bit perturbs the
    element by ~2^-11 of its magnitude, orders above that floor and
    orders below anything training metrics can resolve."""
    out = np.array(host, copy=True)
    flat = out.reshape(-1)
    bits, bit = {2: (np.uint16, 2), 4: (np.uint32, 12),
                 8: (np.uint64, 40)}[out.dtype.itemsize]
    flat.view(bits)[0] ^= bits(1) << bit
    return out


def bitflip_tree(tree, leaf_index: int = 0):
    """Driver-side SDC injection for the local/GSPMD families: one
    mid-mantissa bit of the ``leaf_index``-th float leaf flips.  Pulls and
    re-places through the explicit host choke point, preserving the
    leaf's sharding."""
    import jax
    import jax.numpy as jnp
    from bigdl_tpu.analysis.hostsync import host_pull
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    float_pos = [i for i, l in enumerate(leaves)
                 if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
                 and jnp.asarray(l).size]
    if not float_pos:
        return tree
    pos = float_pos[leaf_index % len(float_pos)]
    leaf = leaves[pos]
    host = _flip_low_bit(np.asarray(host_pull(leaf, what="chaos bitflip")))
    sharding = getattr(leaf, "sharding", None)
    leaves[pos] = (jax.device_put(host, sharding) if sharding is not None
                   else jnp.asarray(host))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def bitflip_one_replica(arr, replica: int):
    """Driver-side SDC injection for the shard_map dp family: flip one
    bit in ONE replica's copy of a replicated array, leaving every other
    copy untouched — the per-device buffers now disagree while the
    logical array still looks healthy, which is exactly what real
    in-HBM corruption does.  Rebuilt without any cross-device
    consistency check (``make_array_from_single_device_arrays`` trusts
    the caller), so agreement is the only detector."""
    import jax
    from bigdl_tpu.analysis.hostsync import host_pull
    shards = sorted(arr.addressable_shards, key=lambda s: s.device.id)
    copies = [np.array(host_pull(s.data, what="chaos bitflip"), copy=True)
              for s in shards]
    r = replica % len(copies)
    copies[r] = _flip_low_bit(copies[r])
    bufs = [jax.device_put(c, s.device) for c, s in zip(copies, shards)]
    return jax.make_array_from_single_device_arrays(
        arr.shape, arr.sharding, bufs)


class DriverIntegrity:
    """Per-run integrity state the trainers hand to the shared driver
    loop: the non-finite leaf-name table (diagnosed divergence), the
    pull cadence, the weight-health gates, and the verdict classifier."""

    def __init__(self, family: str, nf_names: Sequence[str],
                 every_n: int = 0, health: Optional[WeightHealthMonitor]
                 = None):
        self.family = family
        self.nf_names = list(nf_names)
        self.every_n = int(every_n)
        self.health = health
        self.checks = 0

    @property
    def enabled(self) -> bool:
        return self.every_n > 0

    def due(self, neval: int) -> bool:
        return self.enabled and neval % self.every_n == 0

    # -- diagnosed divergence -------------------------------------------

    def describe_nonfinite(self, idx: int) -> str:
        """Suffix for the bad-step log line / DivergenceError: which
        tree and leaf path first went non-finite (empty when the index
        is the all-finite sentinel — e.g. a chaos-injected NaN loss that
        never existed on device)."""
        if idx == NF_SENTINEL or idx < 0:
            return ""
        if idx < len(self.nf_names):
            return f"; first non-finite: {self.nf_names[idx]}"
        return f"; first non-finite: float leaf #{idx}"

    # -- fingerprint verdicts -------------------------------------------

    def _bad_iteration(self, vals: Dict[str, Any], neval: int) -> int:
        it = int(float(vals.get("bad_iter", 0.0)))
        return it if it > 0 else neval

    def check(self, aux, neval: int) -> None:
        """Classify one pulled aux tree.  Raises on corruption; feeds
        health gates and gauges otherwise.  ``aux`` holds DEVICE values
        — the (single, batched) pull happens here, through the choke
        point."""
        from bigdl_tpu.analysis.hostsync import host_pull
        self.checks += 1
        telemetry.counter(
            "Integrity/checks",
            help="driver-side fingerprint verdicts pulled").inc()
        vals = host_pull(
            {k: v for k, v in aux.items() if k != "fpc"},
            what="integrity fingerprints")
        fps_all = vals.get("fps_all")
        if fps_all is not None:
            fps_all = np.asarray(fps_all)
            keys = [fps_all[i].tobytes() for i in range(fps_all.shape[0])]
            _, minority = majority_split(keys)
            if minority:
                self._raise_desync(minority, fps_all, vals, neval)
        if self.family == "gspmd" and "fp_p" in aux:
            # replication is implicit in GSPMD: the traced program holds
            # ONE logical fingerprint, so agreement is verified by
            # bitwise-comparing the replicated output's per-device copies
            minority, keys = replicated_shard_disagreement(aux["fp_p"])
            if minority:
                self._raise_desync(minority,
                                   np.frombuffer(b"".join(keys),
                                                 dtype=np.uint8),
                                   vals, neval)
        if float(vals.get("cont", 0.0)) > 0:
            telemetry.counter(
                "Integrity/continuity_failures",
                help="fused-step fingerprint continuity breaks (silent "
                     "in-memory corruption)").inc()
            it = self._bad_iteration(vals, neval)
            raise IntegrityError(
                f"training-state fingerprint continuity broke at "
                f"iteration {it} (observed at iteration {neval}; "
                f"{self.family} step): parameters or optimizer slots "
                "changed outside the fused step while every value "
                "stayed finite — restoring the latest valid snapshot",
                iteration=it)
        self._observe_health(vals, neval)

    def _raise_desync(self, minority: List[int], fps, vals, neval: int):
        telemetry.counter(
            "Integrity/desync_detected",
            help="cross-replica fingerprint disagreements").inc()
        it = self._bad_iteration(vals, neval)
        raise ReplicaDesyncError(
            f"data-parallel replica(s) {minority} disagree on the "
            f"parameter fingerprint at iteration {it} (observed at "
            f"iteration {neval}; {self.family} step) — healing by "
            "re-broadcasting canonical state from the agreeing "
            "majority", replicas=minority, iteration=it,
            fingerprints=fps)

    # -- weight health ---------------------------------------------------

    def _observe_health(self, vals: Dict[str, Any], neval: int) -> None:
        pn = float(vals.get("pn", float("nan")))
        un = float(vals.get("un", float("nan")))
        gn = float(vals.get("gn", float("nan")))
        if not math.isfinite(pn):
            return
        param_norm = math.sqrt(max(pn, 0.0))
        update_norm = math.sqrt(max(un, 0.0))
        grad_norm = math.sqrt(max(gn, 0.0))
        ratio = update_norm / max(param_norm, 1e-12)
        telemetry.gauge("Integrity/param_norm", summary=True).set(
            param_norm)
        telemetry.gauge("Integrity/update_norm", summary=True).set(
            update_norm)
        telemetry.gauge("Integrity/grad_norm", summary=True).set(
            grad_norm)
        telemetry.gauge("Integrity/update_ratio", summary=True).set(ratio)
        pb = np.asarray(vals.get("pb", ()), dtype=np.float64).ravel()
        ub = np.asarray(vals.get("ub", ()), dtype=np.float64).ravel()
        bucket_ratios = []
        for i in range(min(pb.size, ub.size)):
            r = math.sqrt(max(float(ub[i]), 0.0)) / max(
                math.sqrt(max(float(pb[i]), 0.0)), 1e-12)
            bucket_ratios.append(r)
            telemetry.gauge(
                "Integrity/bucket_update_ratio",
                labels={"bucket": str(i)}).set(r)
        if self.health is not None and self.health.enabled:
            self.health.observe("grad_norm", grad_norm, neval)
            self.health.observe("update_ratio", ratio, neval)
            for i, r in enumerate(bucket_ratios):
                self.health.observe(f"update_ratio_b{i}", r, neval)
