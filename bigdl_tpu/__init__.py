"""BigDL-TPU: a TPU-native deep-learning framework with the capabilities of BigDL.

A brand-new implementation of BigDL's public surface (reference: cnsky2016/BigDL,
surveyed in SURVEY.md) designed for TPU from the ground up:

- the numeric core is JAX/XLA arrays (the reference's ``bigdl.tensor`` strided
  Tensor stack over Intel MKL, see reference ``tensor/Tensor.scala:36``);
- every ``nn`` layer is a *pure function* ``apply(params, input)`` wrapped in a
  thin Torch-style stateful shell (``forward``/``backward``), so whole models
  fuse under one ``jax.jit`` + ``jax.grad`` instead of layer-at-a-time kernels
  (reference ``nn/abstractnn/AbstractModule.scala:213``);
- distributed training replaces the BlockManager fp16 parameter server
  (reference ``parameters/AllReduceParameter.scala:67``) with XLA collectives
  over a ``jax.sharding.Mesh`` — data parallelism via batch sharding, ZeRO-1
  sharded optimizer state via reduce-scatter/all-gather, tensor/sequence
  parallel axes for scale the reference never had.
"""

__version__ = "0.1.0"

from bigdl_tpu.engine import Engine

from bigdl_tpu import telemetry
from bigdl_tpu import nn
from bigdl_tpu import optim
from bigdl_tpu import dataset
from bigdl_tpu import parallel
from bigdl_tpu import utils
from bigdl_tpu import models
from bigdl_tpu import serving
from bigdl_tpu import visualization
