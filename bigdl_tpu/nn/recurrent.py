"""Recurrent stack: cells, Recurrent/BiRecurrent containers, TimeDistributed.

Reference equivalents: ``nn/Cell.scala:44`` (Cell hierarchy), ``nn/RNN.scala``
(RnnCell), ``nn/LSTM.scala:50``, ``nn/LSTMPeephole.scala``, ``nn/GRU.scala:54``,
``nn/ConvLSTMPeephole.scala``, ``nn/Recurrent.scala:33`` (time-dim unroll
container), ``nn/BiRecurrent.scala:33``, ``nn/TimeDistributed.scala:40``.

TPU-native redesign:

- The reference unrolls time in Scala (``nn/Recurrent.scala:203-263``), cloning
  the cell per timestep with shared parameters.  Here the unroll is a single
  ``lax.scan`` — XLA sees one compiled loop body, keeps the carried hidden
  state in registers/VMEM, and the whole scan differentiates through
  ``jax.grad`` (BPTT falls out of autodiff; no stored per-step activation
  management needed — rematerialisation is XLA's job).
- The reference's ``preTopology`` optimisation (hoist time-independent input
  projections out of the loop, ``nn/Cell.scala:50-75``) is expressed as
  :meth:`Cell.project_input`: the input-to-hidden matmul runs once over the
  whole ``(B, T, D)`` block — one large MXU matmul instead of T small ones.
  The scan body then only carries the hidden-to-hidden recurrence.
- Input layout is batch-first ``(B, T, features...)`` matching the reference's
  default ``batchNormal`` mode; the scan internally runs time-major.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Container, Module


def _uniform(rng, shape, stdv, dtype=jnp.float32):
    return jax.random.uniform(rng, shape, dtype, minval=-stdv, maxval=stdv)


# module-level named activations so cells (and models containing them)
# stay picklable for checkpoint/clone_module
def tanh(x):
    return jnp.tanh(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


class Cell(Module):
    """Base class of recurrent cells (reference ``nn/Cell.scala:44``).

    A cell defines three pure pieces:

    - :meth:`init_hidden`   — zero hidden state for a given batch size;
    - :meth:`project_input` — time-independent input projection, applied to the
      full ``(B, T, ...)`` input at once (the reference's ``preTopology``);
    - :meth:`step`          — one recurrence step on a projected timestep.

    ``apply`` gives the cell the reference's standalone Table semantics
    ``[input_t, hidden] -> [output_t, hidden']`` so a cell is usable as a
    plain module too.
    """

    hidden_is_tuple = False

    def init_hidden(self, params, batch_shape):
        raise NotImplementedError

    def project_input(self, params, x, training=False, rng=None):
        """Projection over all timesteps; default identity."""
        return x

    def step(self, params, proj_t, hidden):
        """One step: (projected input_t, hidden) -> (output_t, hidden')."""
        raise NotImplementedError

    def apply(self, params, input, state, training=False, rng=None):
        x_t, hidden = input[0], input[1]
        proj = self.project_input(params, x_t[:, None], training, rng)[:, 0]
        out, new_hidden = self.step(params, proj, hidden)
        return [out, new_hidden], state


class RnnCell(Cell):
    """Vanilla RNN cell: h' = act(x W_ih + b + h W_hh)
    (reference ``nn/RNN.scala``)."""

    def __init__(self, input_size: int, hidden_size: int,
                 activation=tanh, w_regularizer=None, u_regularizer=None,
                 b_regularizer=None, name=None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        self.w_regularizer = w_regularizer
        self.u_regularizer = u_regularizer
        self.b_regularizer = b_regularizer

    def _init_params(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        stdv = 1.0 / math.sqrt(self.hidden_size)
        return {"w_ih": _uniform(k1, (self.input_size, self.hidden_size), stdv),
                "w_hh": _uniform(k2, (self.hidden_size, self.hidden_size), stdv),
                "bias": _uniform(k3, (self.hidden_size,), stdv)}

    def init_hidden(self, params, batch_shape):
        # follow the (possibly bf16-cast) parameter dtype: an f32 hidden
        # state would promote every recurrent matmul of a bf16 forward
        return jnp.zeros(tuple(batch_shape) + (self.hidden_size,),
                         dtype=params["w_hh"].dtype)

    def project_input(self, params, x, training=False, rng=None):
        return x @ params["w_ih"] + params["bias"]

    def step(self, params, proj_t, hidden):
        h = self.activation(proj_t + hidden @ params["w_hh"])
        return h, h


class LSTM(Cell):
    """LSTM cell, gate order (i, f, g, o) (reference ``nn/LSTM.scala:50``).

    The four gate projections are one fused ``(D, 4H)`` matmul.  ``p`` is the
    reference's dropout probability on the input projections; masks for all
    timesteps are drawn up front so the scan body stays deterministic.
    """

    hidden_is_tuple = True

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0,
                 activation=tanh, inner_activation=sigmoid,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None,
                 name=None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.p = p
        self.activation = activation
        self.inner_activation = inner_activation
        self.w_regularizer = w_regularizer
        self.u_regularizer = u_regularizer
        self.b_regularizer = b_regularizer

    def is_stochastic(self):
        return self.p > 0

    def _init_params(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        H = self.hidden_size
        stdv = 1.0 / math.sqrt(H)
        return {"w_ih": _uniform(k1, (self.input_size, 4 * H), stdv),
                "w_hh": _uniform(k2, (H, 4 * H), stdv),
                "bias": _uniform(k3, (4 * H,), stdv)}

    def init_hidden(self, params, batch_shape):
        z = jnp.zeros(tuple(batch_shape) + (self.hidden_size,),
                      dtype=params["w_hh"].dtype)
        return (z, z)

    def project_input(self, params, x, training=False, rng=None):
        if training and self.p > 0 and rng is not None:
            keep = 1.0 - self.p
            mask = jax.random.bernoulli(rng, keep, x.shape) / keep
            x = x * mask
        return x @ params["w_ih"] + params["bias"]

    def step(self, params, proj_t, hidden):
        h, c = hidden
        H = self.hidden_size
        z = proj_t + h @ params["w_hh"]
        i = self.inner_activation(z[..., 0:H])
        f = self.inner_activation(z[..., H:2 * H])
        g = self.activation(z[..., 2 * H:3 * H])
        o = self.inner_activation(z[..., 3 * H:4 * H])
        c2 = f * c + i * g
        h2 = o * self.activation(c2)
        return h2, (h2, c2)


class LSTMPeephole(LSTM):
    """LSTM with peephole connections from the cell state into the gates
    (reference ``nn/LSTMPeephole.scala``)."""

    def _init_params(self, rng):
        base = super()._init_params(rng)
        k = jax.random.fold_in(rng, 7)
        k1, k2, k3 = jax.random.split(k, 3)
        H = self.hidden_size
        stdv = 1.0 / math.sqrt(H)
        base.update({"w_ci": _uniform(k1, (H,), stdv),
                     "w_cf": _uniform(k2, (H,), stdv),
                     "w_co": _uniform(k3, (H,), stdv)})
        return base

    def step(self, params, proj_t, hidden):
        h, c = hidden
        H = self.hidden_size
        z = proj_t + h @ params["w_hh"]
        i = self.inner_activation(z[..., 0:H] + c * params["w_ci"])
        f = self.inner_activation(z[..., H:2 * H] + c * params["w_cf"])
        g = self.activation(z[..., 2 * H:3 * H])
        c2 = f * c + i * g
        o = self.inner_activation(z[..., 3 * H:4 * H] + c2 * params["w_co"])
        h2 = o * self.activation(c2)
        return h2, (h2, c2)


class GRU(Cell):
    """GRU cell, gates (r, z) + candidate n (reference ``nn/GRU.scala:54``)."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None,
                 name=None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.p = p
        self.w_regularizer = w_regularizer
        self.u_regularizer = u_regularizer
        self.b_regularizer = b_regularizer

    def is_stochastic(self):
        return self.p > 0

    def _init_params(self, rng):
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        H = self.hidden_size
        stdv = 1.0 / math.sqrt(H)
        return {"w_ih": _uniform(k1, (self.input_size, 3 * H), stdv),
                "w_hh": _uniform(k2, (H, 3 * H), stdv),
                "b_ih": _uniform(k3, (3 * H,), stdv),
                "b_hh": _uniform(k4, (3 * H,), stdv)}

    def init_hidden(self, params, batch_shape):
        return jnp.zeros(tuple(batch_shape) + (self.hidden_size,),
                         dtype=params["w_hh"].dtype)

    def project_input(self, params, x, training=False, rng=None):
        if training and self.p > 0 and rng is not None:
            keep = 1.0 - self.p
            mask = jax.random.bernoulli(rng, keep, x.shape) / keep
            x = x * mask
        return x @ params["w_ih"] + params["b_ih"]

    def step(self, params, proj_t, hidden):
        H = self.hidden_size
        hz = hidden @ params["w_hh"] + params["b_hh"]
        r = jax.nn.sigmoid(proj_t[..., 0:H] + hz[..., 0:H])
        z = jax.nn.sigmoid(proj_t[..., H:2 * H] + hz[..., H:2 * H])
        n = jnp.tanh(proj_t[..., 2 * H:3 * H] + r * hz[..., 2 * H:3 * H])
        h2 = (1.0 - z) * n + z * hidden
        return h2, h2


class ConvLSTMPeephole(Cell):
    """Convolutional LSTM with peepholes over NCHW maps
    (reference ``nn/ConvLSTMPeephole.scala``).

    All four gates come from one fused conv with ``4 * output_size`` output
    channels — a single large MXU convolution per step.
    """

    hidden_is_tuple = True
    _spatial_dims = 2

    def __init__(self, input_size: int, output_size: int,
                 kernel_i: int = 3, kernel_c: int = 3, stride: int = 1,
                 with_peephole: bool = True, name=None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.kernel_i = kernel_i
        self.kernel_c = kernel_c
        self.stride = stride
        self.with_peephole = with_peephole
        self._spatial_shape = None  # bound at first init_hidden

    def _init_params(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        H, C = self.output_size, self.input_size
        nd = self._spatial_dims
        ki = (self.kernel_i,) * nd
        kc = (self.kernel_c,) * nd
        fan_in = C * self.kernel_i ** nd
        stdv = 1.0 / math.sqrt(fan_in)
        p = {"w_ih": _uniform(k1, (4 * H, C) + ki, stdv),
             "w_hh": _uniform(k2, (4 * H, H) + kc, stdv),
             "bias": _uniform(k3, (4 * H,), stdv)}
        if self.with_peephole:
            kk = jax.random.split(jax.random.fold_in(rng, 7), 3)
            ones = (1,) * nd
            p.update({"w_ci": _uniform(kk[0], (H,) + ones, stdv),
                      "w_cf": _uniform(kk[1], (H,) + ones, stdv),
                      "w_co": _uniform(kk[2], (H,) + ones, stdv)})
        return p

    def _dn(self, x):
        nd = self._spatial_dims
        spec = "NCHW" if nd == 2 else "NCDHW"
        kspec = "OIHW" if nd == 2 else "OIDHW"
        return lax.conv_dimension_numbers(x.shape, (1, 1) + (1,) * nd,
                                          (spec, kspec, spec))

    def _conv(self, x, w):
        nd = self._spatial_dims
        return lax.conv_general_dilated(
            x, w, window_strides=(1,) * nd, padding="SAME",
            dimension_numbers=self._dn(x))

    def init_hidden(self, params, batch_shape):
        if self._spatial_shape is None:
            raise RuntimeError("ConvLSTMPeephole hidden spatial shape unknown "
                               "before the first forward")
        shape = tuple(batch_shape) + (self.output_size,) + self._spatial_shape
        z = jnp.zeros(shape, dtype=params["w_hh"].dtype)
        return (z, z)

    def project_input(self, params, x, training=False, rng=None):
        # x: (B, T, C, *spatial) — fold T into the batch for one big conv
        B, T = x.shape[0], x.shape[1]
        self._spatial_shape = tuple(x.shape[3:])
        flat = x.reshape((B * T,) + x.shape[2:])
        nd = self._spatial_dims
        bias = params["bias"].reshape((1, -1) + (1,) * nd)
        out = self._conv(flat, params["w_ih"]) + bias
        return out.reshape((B, T) + out.shape[1:])

    def step(self, params, proj_t, hidden):
        h, c = hidden
        H = self.output_size
        z = proj_t + self._conv(h, params["w_hh"])
        zi, zf, zg, zo = (z[:, 0:H], z[:, H:2 * H],
                          z[:, 2 * H:3 * H], z[:, 3 * H:4 * H])
        if self.with_peephole:
            zi = zi + c * params["w_ci"]
            zf = zf + c * params["w_cf"]
        i = jax.nn.sigmoid(zi)
        f = jax.nn.sigmoid(zf)
        g = jnp.tanh(zg)
        c2 = f * c + i * g
        if self.with_peephole:
            zo = zo + c2 * params["w_co"]
        o = jax.nn.sigmoid(zo)
        h2 = o * jnp.tanh(c2)
        return h2, (h2, c2)


class ConvLSTMPeephole3D(ConvLSTMPeephole):
    """3-D variant (reference ``nn/ConvLSTMPeephole3D.scala``)."""

    _spatial_dims = 3


class Recurrent(Container):
    """Time-dimension unroll container (reference ``nn/Recurrent.scala:33``).

    ``add(cell)`` then forward a ``(B, T, features...)`` batch; output is the
    per-timestep cell output stacked back to ``(B, T, ...)``.  The unroll is a
    ``lax.scan`` over the time-major projected input.
    """

    def __init__(self, name=None):
        super().__init__(name)
        self._last_hidden = None
        self._init_hidden_override = None

    def add(self, module: Module) -> "Recurrent":
        if not isinstance(module, Cell):
            raise ValueError("Recurrent accepts a Cell, got "
                             f"{type(module).__name__}")
        if self.children:
            raise ValueError("Recurrent holds exactly one Cell")
        return super().add(module)

    @property
    def cell(self) -> Cell:
        return self.children[0]

    def set_hidden_state(self, hidden) -> "Recurrent":
        """(reference ``Recurrent.setHiddenState``)"""
        self._init_hidden_override = hidden
        return self

    def get_hidden_state(self):
        """(reference ``Recurrent.getHiddenState``) — hidden after the last
        forward (shell-side convenience; not part of the pure core)."""
        return self._last_hidden

    def apply(self, params, input, state, training=False, rng=None):
        cell = self.cell
        cp = params[0]
        proj = cell.project_input(cp, input, training=training, rng=rng)
        if self._init_hidden_override is not None:
            h0 = self._init_hidden_override
        else:
            h0 = cell.init_hidden(cp, (input.shape[0],))

        # time-major for the scan
        proj_tm = jnp.moveaxis(proj, 1, 0)

        def body(h, x_t):
            out, h2 = cell.step(cp, x_t, h)
            return h2, out

        h_final, outs = lax.scan(body, h0, proj_tm)
        # cache for get_hidden_state() only when not under a jit trace —
        # a leaked tracer would poison clone_module/checkpoint pickling
        if not any(isinstance(l, jax.core.Tracer)
                   for l in jax.tree_util.tree_leaves(h_final)):
            self._last_hidden = h_final
        return jnp.moveaxis(outs, 0, 1), state

    def __getstate__(self):
        d = super().__getstate__()
        d["_last_hidden"] = None
        return d


class BiRecurrent(Container):
    """Bidirectional wrapper (reference ``nn/BiRecurrent.scala:33``).

    Runs the cell forward and a clone backward over time, merging outputs
    with ``merge`` ('add', the reference's CAddTable default, or 'concat').
    """

    def __init__(self, merge: str = "add", name=None):
        super().__init__(name)
        if merge not in ("add", "concat"):
            raise ValueError(f"merge must be add|concat, got {merge}")
        self.merge = merge

    def add(self, module: Module) -> "BiRecurrent":
        if not isinstance(module, Cell):
            raise ValueError("BiRecurrent accepts a Cell")
        if len(self.children) >= 2:
            raise ValueError("BiRecurrent holds forward and reverse cells only")
        super().add(module)
        if len(self.children) == 1:
            super().add(module.clone_module())
        return self

    def apply(self, params, input, state, training=False, rng=None):
        fwd_cell, bwd_cell = self.children[0], self.children[1]

        def run(cell, cp, x, key):
            proj = cell.project_input(cp, x, training=training, rng=key)
            h0 = cell.init_hidden(cp, (x.shape[0],))
            proj_tm = jnp.moveaxis(proj, 1, 0)

            def body(h, x_t):
                out, h2 = cell.step(cp, x_t, h)
                return h2, out

            _, outs = lax.scan(body, h0, proj_tm)
            return jnp.moveaxis(outs, 0, 1)

        k1 = k2 = None
        if rng is not None:
            k1, k2 = jax.random.split(rng)
        out_f = run(fwd_cell, params[0], input, k1)
        out_b = run(bwd_cell, params[1], jnp.flip(input, axis=1), k2)
        out_b = jnp.flip(out_b, axis=1)
        if self.merge == "add":
            return out_f + out_b, state
        return jnp.concatenate([out_f, out_b], axis=-1), state


class TimeDistributed(Container):
    """Apply the wrapped layer independently at every timestep
    (reference ``nn/TimeDistributed.scala:40``): fold T into the batch so the
    inner layer sees one ``(B*T, ...)`` mega-batch — exactly the large-batch
    shape the MXU wants."""

    def __init__(self, layer: Optional[Module] = None, name=None):
        super().__init__(name)
        if layer is not None:
            self.add(layer)

    def apply(self, params, input, state, training=False, rng=None):
        B, T = input.shape[0], input.shape[1]
        flat = input.reshape((B * T,) + input.shape[2:])
        out, new_state = self.children[0].apply(
            params[0], flat, state[0], training=training, rng=rng)
        return out.reshape((B, T) + out.shape[1:]), [new_state]


class BinaryTreeLSTM(Module):
    """Binary constituency TreeLSTM (reference ``nn/BinaryTreeLSTM.scala:36``).

    TPU-native formulation: instead of Scala-side recursion over a tree object,
    the tree is data — input is ``[embeddings, tree]`` where

    - ``embeddings``: ``(B, n_leaves, D)`` leaf word vectors;
    - ``tree``: ``(B, n_nodes, 2)`` int32 child indices in *topological order*
      (children precede parents).  Node ``i < n_leaves`` is leaf ``i``; index
      ``-1`` marks an unused child slot.  Padded trees (rows of ``-1``) are
      skipped by masking.

    The recursion becomes a ``lax.scan`` over the node list with gathers into
    the growing (h, c) buffers — compiler-friendly, fixed shapes.
    """

    def __init__(self, input_size: int, hidden_size: int, name=None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size

    def _init_params(self, rng):
        ks = jax.random.split(rng, 4)
        D, H = self.input_size, self.hidden_size
        stdv = 1.0 / math.sqrt(H)
        return {
            # leaf transform
            "w_leaf": _uniform(ks[0], (D, 3 * H), stdv),   # i, o, u
            "b_leaf": _uniform(ks[1], (3 * H,), stdv),
            # composer: [h_l, h_r] -> i, f_l, f_r, o, u
            "w_comp": _uniform(ks[2], (2 * H, 5 * H), stdv),
            "b_comp": _uniform(ks[3], (5 * H,), stdv),
        }

    def apply(self, params, input, state, training=False, rng=None):
        emb, tree = input[0], input[1]
        B, L, D = emb.shape
        N = L + tree.shape[1]
        H = self.hidden_size

        # leaves: fused (B, L, 3H) projection
        z = emb @ params["w_leaf"] + params["b_leaf"]
        i = jax.nn.sigmoid(z[..., 0:H])
        o = jax.nn.sigmoid(z[..., H:2 * H])
        u = jnp.tanh(z[..., 2 * H:3 * H])
        c_leaf = i * u
        h_leaf = o * jnp.tanh(c_leaf)

        h_buf = jnp.concatenate(
            [h_leaf, jnp.zeros((B, tree.shape[1], H), dtype=h_leaf.dtype)], 1)
        c_buf = jnp.concatenate(
            [c_leaf, jnp.zeros((B, tree.shape[1], H), dtype=c_leaf.dtype)], 1)

        def body(carry, node):
            h_buf, c_buf, idx = carry
            l, r = node[:, 0], node[:, 1]
            valid = (l >= 0) & (r >= 0)
            li = jnp.maximum(l, 0)
            ri = jnp.maximum(r, 0)
            hl = jnp.take_along_axis(h_buf, li[:, None, None].repeat(H, 2), 1)[:, 0]
            hr = jnp.take_along_axis(h_buf, ri[:, None, None].repeat(H, 2), 1)[:, 0]
            cl = jnp.take_along_axis(c_buf, li[:, None, None].repeat(H, 2), 1)[:, 0]
            cr = jnp.take_along_axis(c_buf, ri[:, None, None].repeat(H, 2), 1)[:, 0]
            zc = jnp.concatenate([hl, hr], -1) @ params["w_comp"] + params["b_comp"]
            ig = jax.nn.sigmoid(zc[:, 0:H])
            fl = jax.nn.sigmoid(zc[:, H:2 * H])
            fr = jax.nn.sigmoid(zc[:, 2 * H:3 * H])
            og = jax.nn.sigmoid(zc[:, 3 * H:4 * H])
            ug = jnp.tanh(zc[:, 4 * H:5 * H])
            c_new = ig * ug + fl * cl + fr * cr
            h_new = og * jnp.tanh(c_new)
            mask = valid[:, None].astype(h_new.dtype)
            h_new = h_new * mask
            c_new = c_new * mask
            onehot = jax.nn.one_hot(idx, N, dtype=h_buf.dtype)[None, :, None]
            h_buf = h_buf * (1 - onehot) + h_new[:, None, :] * onehot
            c_buf = c_buf * (1 - onehot) + c_new[:, None, :] * onehot
            return (h_buf, c_buf, idx + 1), h_new

        (h_buf, _, _), node_h = lax.scan(
            body, (h_buf, c_buf, jnp.int32(L)), jnp.moveaxis(tree, 1, 0))
        # (B, n_internal, H) internal-node hiddens in topological order
        return jnp.moveaxis(node_h, 0, 1), state


TreeLSTM = BinaryTreeLSTM
