"""TF-import helper ops.

Reference equivalents: ``nn/tf/{Const, Fill, Shape, SplitAndSelect,
StrideSlice}.scala`` — small ops the TensorFlow importer needs to express
GraphDef nodes that have no Torch-layer counterpart.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Module


class Const(Module):
    """Emit a fixed tensor, ignoring the input (reference
    ``nn/tf/Const.scala``: the input only rides the graph topology)."""

    def __init__(self, value, name=None):
        super().__init__(name)
        self.value = np.asarray(value)

    def apply(self, params, input, state, training=False, rng=None):
        return jnp.asarray(self.value), state


class Fill(Module):
    """Fill a shape with a scalar.  Input: Table (shape vector, value)
    (reference ``nn/tf/Fill.scala``).  The output shape must be static for
    XLA, so the shell forward runs eagerly (shape read from a concrete
    array); inside a larger jitted graph the importer folds Fill against
    its Const shape instead."""

    def apply(self, params, input, state, training=False, rng=None):
        shape, value = input[0], input[1]
        dims = tuple(int(d) for d in np.asarray(shape).reshape(-1))
        return jnp.full(dims, jnp.asarray(value).reshape(())), state

    def _jitted(self):
        # dynamic output shape: cannot trace; eager shell only
        return lambda p, x, s, r: self.apply(p, x, s, rng=r)


class Shape(Module):
    """Input's shape as an int32 vector (reference ``nn/tf/Shape.scala``)."""

    def apply(self, params, input, state, training=False, rng=None):
        return jnp.asarray(input.shape, jnp.int32), state


class SplitAndSelect(Module):
    """Split ``dimension`` into ``num_split`` equal slices, emit the
    ``index``-th (both 1-based; negative dimension counts from the end —
    reference ``nn/tf/SplitAndSelect.scala``)."""

    def __init__(self, dimension: int, index: int, num_split: int, name=None):
        super().__init__(name)
        self.dimension = dimension
        self.index = index
        self.num_split = num_split

    def apply(self, params, input, state, training=False, rng=None):
        dim = (input.ndim + self.dimension if self.dimension < 0
               else self.dimension - 1)
        size = input.shape[dim]
        if size % self.num_split != 0:
            raise ValueError(
                f"numSplit {self.num_split} must evenly divide dim size "
                f"{size} (reference SplitAndSelect require)")
        length = size // self.num_split
        start = (self.index - 1) * length
        idx = [slice(None)] * input.ndim
        idx[dim] = slice(start, start + length)
        return input[tuple(idx)], state


class StrideSlice(Module):
    """Chained narrows: specs are (dim, start, end) 1-based, end exclusive,
    stride 1 (reference ``nn/tf/StrideSlice.scala`` — which also only
    supports stride 1)."""

    def __init__(self, slice_specs: Sequence[Tuple[int, int, int]], name=None):
        super().__init__(name)
        self.slice_specs = [tuple(int(v) for v in s) for s in slice_specs]

    def apply(self, params, input, state, training=False, rng=None):
        out = input
        for (dim, start, end) in self.slice_specs:
            idx = [slice(None)] * out.ndim
            idx[dim - 1] = slice(start - 1, end - 1)
            out = out[tuple(idx)]
        return out, state
