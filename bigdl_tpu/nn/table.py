"""Table (multi-input/multi-output) plumbing layers and branch containers.

Reference: ``nn/Concat.scala``, ``nn/ConcatTable.scala``, ``nn/ParallelTable.scala``,
``nn/MapTable.scala``, ``nn/JoinTable.scala``, ``nn/SplitTable.scala``,
``nn/SelectTable.scala``, ``nn/NarrowTable.scala``, ``nn/FlattenTable.scala``,
``nn/MixtureTable.scala``, ``nn/CAddTable.scala`` (+ CSub/CMul/CDiv/CMax/CMin),
``nn/PairwiseDistance.scala``, ``nn/CosineDistance.scala``.

A Table is a python list/tuple of activities (reference ``utils/Table.scala:34``).
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from bigdl_tpu.nn.module import Module, Container, _child_rng
from bigdl_tpu.nn.structural import _axis


class Concat(Container):
    """Apply each child to the SAME input, concat outputs along 1-based dim
    (reference ``nn/Concat.scala``)."""

    def __init__(self, dimension: int, name=None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, input, state, training=False, rng=None):
        outs, new_states = [], []
        for i, child in enumerate(self.children):
            o, s = child.apply(params[i], input, state[i], training=training,
                               rng=_child_rng(rng, i))
            outs.append(o)
            new_states.append(s)
        ax = _axis(self.dimension, outs[0].ndim)
        return jnp.concatenate(outs, axis=ax), new_states


class ConcatTable(Container):
    """Apply each child to the same input; output is the Table of results
    (reference ``nn/ConcatTable.scala``)."""

    def apply(self, params, input, state, training=False, rng=None):
        outs, new_states = [], []
        for i, child in enumerate(self.children):
            o, s = child.apply(params[i], input, state[i], training=training,
                               rng=_child_rng(rng, i))
            outs.append(o)
            new_states.append(s)
        return outs, new_states


class ParallelTable(Container):
    """i-th child applied to i-th table element (reference ``nn/ParallelTable.scala``)."""

    def apply(self, params, input, state, training=False, rng=None):
        outs, new_states = [], []
        for i, child in enumerate(self.children):
            o, s = child.apply(params[i], input[i], state[i], training=training,
                               rng=_child_rng(rng, i))
            outs.append(o)
            new_states.append(s)
        return outs, new_states


class MapTable(Container):
    """One shared child applied to every table element
    (reference ``nn/MapTable.scala``).  Parameters are shared — the single
    child's params are used for every element."""

    def __init__(self, module: Optional[Module] = None, name=None):
        super().__init__(name)
        if module is not None:
            self.add(module)

    def apply(self, params, input, state, training=False, rng=None):
        child = self.children[0]
        outs = []
        s = state[0]
        for i, x in enumerate(input):
            o, s = child.apply(params[0], x, s, training=training,
                               rng=_child_rng(rng, i))
            outs.append(o)
        return outs, [s]


class JoinTable(Module):
    """Concat a Table of tensors along a 1-based dim
    (reference ``nn/JoinTable.scala``)."""

    def __init__(self, dimension: int, n_input_dims: int = -1, name=None):
        super().__init__(name)
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def apply(self, params, input, state, training=False, rng=None):
        ax = _axis(self.dimension, input[0].ndim, self.n_input_dims)
        return jnp.concatenate(list(input), axis=ax), state


class SplitTable(Module):
    """Split a tensor into a Table along a 1-based dim
    (reference ``nn/SplitTable.scala``)."""

    def __init__(self, dimension: int, n_input_dims: int = -1, name=None):
        super().__init__(name)
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def apply(self, params, input, state, training=False, rng=None):
        ax = _axis(self.dimension, input.ndim, self.n_input_dims)
        n = input.shape[ax]
        outs = [jnp.take(input, i, axis=ax) for i in range(n)]
        return outs, state


class SelectTable(Module):
    """Select the i-th (1-based) element of a Table
    (reference ``nn/SelectTable.scala``)."""

    def __init__(self, index: int, name=None):
        super().__init__(name)
        self.index = index

    def apply(self, params, input, state, training=False, rng=None):
        i = self.index - 1 if self.index > 0 else len(input) + self.index
        return input[i], state


class NarrowTable(Module):
    """Slice a Table (reference ``nn/NarrowTable.scala``)."""

    def __init__(self, offset: int, length: int = 1, name=None):
        super().__init__(name)
        self.offset = offset
        self.length = length

    def apply(self, params, input, state, training=False, rng=None):
        length = self.length
        if length < 0:
            length = len(input) - self.offset + 2 + length
        return list(input)[self.offset - 1: self.offset - 1 + length], state


class FlattenTable(Module):
    """Flatten nested Tables into one flat Table (reference ``nn/FlattenTable.scala``)."""

    def apply(self, params, input, state, training=False, rng=None):
        out: List = []

        def rec(x):
            if isinstance(x, (list, tuple)):
                for v in x:
                    rec(v)
            else:
                out.append(x)

        rec(input)
        return out, state


class MixtureTable(Module):
    """Mixture-of-experts blend: input [gates (N,E), experts Table/tensor]
    (reference ``nn/MixtureTable.scala``)."""

    def __init__(self, dim: int = -1, name=None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, input, state, training=False, rng=None):
        gates, experts = input[0], input[1]
        if isinstance(experts, (list, tuple)):
            stacked = jnp.stack(list(experts), axis=1)  # (N, E, ...)
        else:
            stacked = experts
        gshape = gates.shape + (1,) * (stacked.ndim - gates.ndim)
        return jnp.sum(stacked * jnp.reshape(gates, gshape), axis=1), state


class _BinaryTableOp(Module):
    layout_role = "agnostic"   # elementwise over the table entries

    def _op(self, a, b):
        raise NotImplementedError

    def apply(self, params, input, state, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = self._op(out, x)
        return out, state


class CAddTable(_BinaryTableOp):
    """Elementwise sum of a Table (reference ``nn/CAddTable.scala``)."""

    def __init__(self, inplace: bool = False, name=None):
        super().__init__(name)

    def _op(self, a, b):
        return a + b


class CSubTable(_BinaryTableOp):
    """Elementwise a - b of a Table [a, b] (reference ``nn/CSubTable.scala``)."""

    def _op(self, a, b):
        return a - b


class CMulTable(_BinaryTableOp):
    """Elementwise product of all Table elements (reference ``nn/CMulTable.scala``)."""

    def _op(self, a, b):
        return a * b


class CDivTable(_BinaryTableOp):
    """Elementwise a / b of a Table [a, b] (reference ``nn/CDivTable.scala``)."""

    def _op(self, a, b):
        return a / b


class CMaxTable(_BinaryTableOp):
    """Elementwise maximum of all Table elements (reference ``nn/CMaxTable.scala``)."""

    def _op(self, a, b):
        return jnp.maximum(a, b)


class CMinTable(_BinaryTableOp):
    """Elementwise minimum of all Table elements (reference ``nn/CMinTable.scala``)."""

    def _op(self, a, b):
        return jnp.minimum(a, b)


class PairwiseDistance(Module):
    """L-p distance between table elements [a, b]
    (reference ``nn/PairwiseDistance.scala``)."""

    def __init__(self, norm: int = 2, name=None):
        super().__init__(name)
        self.norm = norm

    def apply(self, params, input, state, training=False, rng=None):
        a, b = input[0], input[1]
        d = jnp.abs(a - b) ** self.norm
        return jnp.sum(d, axis=-1) ** (1.0 / self.norm), state


class CosineDistance(Module):
    """Cosine similarity between table elements [a, b]
    (reference ``nn/CosineDistance.scala``)."""

    def apply(self, params, input, state, training=False, rng=None):
        a, b = input[0], input[1]
        an = jnp.maximum(jnp.linalg.norm(a, axis=-1), 1e-12)
        bn = jnp.maximum(jnp.linalg.norm(b, axis=-1), 1e-12)
        return jnp.sum(a * b, axis=-1) / (an * bn), state
