"""Parameter initialization methods.

Reference: ``nn/InitializationMethod.scala`` + ``nn/abstractnn/Initializable.scala``
(Zeros/Ones/Const/RandomUniform/RandomNormal/Xavier/BilinearFiller, with
``VariableFormat`` fan-in/fan-out conventions).

Here each method is a function ``(rng, shape, fan_in, fan_out) -> array``;
layers compute their own fans from their kernel geometry (the role
``VariableFormat`` plays in the reference).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class InitializationMethod:
    def __call__(self, rng, shape: Sequence[int],
                 fan_in: Optional[int] = None,
                 fan_out: Optional[int] = None,
                 dtype=jnp.float32) -> jnp.ndarray:
        raise NotImplementedError


class Zeros(InitializationMethod):
    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)


class Ones(InitializationMethod):
    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        return jnp.ones(shape, dtype)


class ConstInitMethod(InitializationMethod):
    def __init__(self, value: float):
        self.value = value

    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


class RandomUniform(InitializationMethod):
    """Uniform in [lower, upper]; with no bounds, the Torch default
    U(-1/sqrt(fan_in), 1/sqrt(fan_in))."""

    def __init__(self, lower: Optional[float] = None, upper: Optional[float] = None):
        self.lower, self.upper = lower, upper

    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        if self.lower is None:
            bound = 1.0 / math.sqrt(max(1, fan_in or 1))
            lo, hi = -bound, bound
        else:
            lo, hi = self.lower, self.upper
        return jax.random.uniform(rng, tuple(shape), dtype, lo, hi)


class RandomNormal(InitializationMethod):
    def __init__(self, mean: float = 0.0, stdv: float = 1.0):
        self.mean, self.stdv = mean, stdv

    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        return self.mean + self.stdv * jax.random.normal(rng, tuple(shape), dtype)


class Xavier(InitializationMethod):
    """Glorot uniform: U(-sqrt(6/(fan_in+fan_out)), +...)."""

    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        fi = fan_in or int(np.prod(shape[:-1])) or 1
        fo = fan_out or shape[-1]
        bound = math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(rng, tuple(shape), dtype, -bound, bound)


class MsraFiller(InitializationMethod):
    """He initialization (kaiming normal)."""

    def __init__(self, var_fan_in: bool = True):
        self.var_fan_in = var_fan_in

    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        fan = (fan_in if self.var_fan_in else fan_out) or 1
        std = math.sqrt(2.0 / fan)
        return std * jax.random.normal(rng, tuple(shape), dtype)


class BilinearFiller(InitializationMethod):
    """Bilinear upsampling kernel (for SpatialFullConvolution).
    Expects shape (kh, kw, ...) trailing dims broadcast."""

    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        kh, kw = shape[0], shape[1]
        f_h, f_w = math.ceil(kh / 2.0), math.ceil(kw / 2.0)
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        ys = np.arange(kh)[:, None]
        xs = np.arange(kw)[None, :]
        kern = (1 - np.abs(ys / f_h - c_h)) * (1 - np.abs(xs / f_w - c_w))
        kern = kern.reshape(kern.shape + (1,) * (len(shape) - 2))
        return jnp.broadcast_to(jnp.asarray(kern, dtype), tuple(shape))
