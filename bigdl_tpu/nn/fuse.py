"""Inference-time graph rewrites: conv + BatchNorm folding.

At inference BatchNorm is an affine per-channel map built from FROZEN
running statistics, so it folds exactly into the preceding convolution's
weights::

    s = gamma * rsqrt(running_var + eps)
    w' = w * s          (per output channel, HWIO trailing axis)
    b' = b * s + (beta - running_mean * s)

One conv replaces a conv + BN pair — fewer kernels, less HBM traffic, and
(together with the channels-last path, ``nn/layout.py``) the shape the
Predictor/evaluator hot loop should run.  Reference BigDL has no equivalent
(its Predictor executes the module graph as built); this mirrors what every
serving stack (TensorRT, OpenVINO, tf.graph_transforms) does before deploy.

Training semantics are NOT preserved — batch statistics differ from running
statistics — so fold a clone for serving (``Predictor(model, fold_bn=True)``
does exactly that) and keep the original for training.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Container, Module, Sequential
from bigdl_tpu.nn.conv import SpatialConvolution
from bigdl_tpu.nn.normalization import SpatialBatchNormalization
from bigdl_tpu.nn.structural import Identity

__all__ = ["fold_conv_bn"]


def _bn_scale_shift(bn: SpatialBatchNormalization, params, state):
    # identical arithmetic to BatchNormalization.apply's eval path (same
    # rsqrt), so folded outputs match to float-associativity error
    inv = jax.lax.rsqrt(state["running_var"] + bn.eps)
    if bn.affine:
        scale = params["weight"] * inv
        shift = params["bias"] - state["running_mean"] * scale
    else:
        scale = inv
        shift = -state["running_mean"] * scale
    return scale, shift


def _foldable(conv: Module, bn: Module) -> bool:
    return (isinstance(conv, SpatialConvolution) and
            isinstance(bn, SpatialBatchNormalization) and
            bn.n_output == conv.n_output_plane)


def fold_conv_bn(model: Module) -> Module:
    """Fold every ``SpatialConvolution -> SpatialBatchNormalization``
    adjacency (within any ``Sequential``) into the convolution, replacing
    the BN with ``Identity``.  In place; returns ``model``.

    The rewrite uses the BN's RUNNING statistics, i.e. it freezes the
    module at its inference behaviour — only use the folded model for
    eval/serving.  Outputs match the unfolded eval forward to float
    rounding (<= 1e-5, asserted in tests/test_layout.py).
    """
    model._ensure_init()
    if isinstance(model, Container):
        # share one params/state tree across the nesting before editing in
        # place (clone_module leaves per-container copies behind)
        model._adopt()
    _fold_in(model)
    if isinstance(model, Container):
        model._adopt()
    model.clear_jit_cache()
    return model


def _fold_in(container: Module) -> None:
    if not isinstance(container, Container):
        return
    if isinstance(container, Sequential):
        for i in range(len(container.children) - 1):
            conv, bn = container.children[i], container.children[i + 1]
            if not _foldable(conv, bn):
                continue
            cp = container._params[i]
            bp = container._params[i + 1]
            bs = container._state[i + 1]
            scale, shift = _bn_scale_shift(bn, bp, bs)
            scale = scale.astype(cp["weight"].dtype)
            shift = shift.astype(cp["weight"].dtype)
            cp["weight"] = cp["weight"] * scale    # HWIO: O is trailing
            if conv.with_bias:
                cp["bias"] = cp["bias"] * scale + shift
            else:
                conv.with_bias = True
                cp["bias"] = shift
                container._grads[i]["bias"] = jnp.zeros_like(shift)
            ident = Identity()
            ident._ensure_init()
            container.children[i + 1] = ident
            container._params[i + 1] = ident._params
            container._state[i + 1] = ident._state
            container._grads[i + 1] = ident._grads
    for c in container.children:
        _fold_in(c)
