"""Activation layers + stochastic regularizers.

Reference: the activation files in ``nn/`` (ReLU.scala, Tanh.scala, ...,
HardShrink.scala), ``nn/Dropout.scala:44``, ``nn/L1Penalty.scala``,
``nn/PReLU.scala``, ``nn/RReLU.scala``.

All are stateless elementwise maps — the VPU's bread and butter — and fuse
into adjacent matmuls under XLA, replacing the reference's MKL VML dispatch
(``tensor/TensorNumeric.scala:195-340``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class _Elementwise(Module):
    layout_role = "agnostic"   # pointwise: any data format passes through

    def _fn(self, x):
        raise NotImplementedError

    def apply(self, params, input, state, training=False, rng=None):
        return self._fn(input), state


class ReLU(_Elementwise):
    """Rectified linear max(x, 0) (reference ``nn/ReLU.scala``)."""

    def __init__(self, ip: bool = False, name=None):
        super().__init__(name)
        self.inplace = ip  # meaningless under XLA; kept for API parity

    def _fn(self, x):
        return jax.nn.relu(x)


class ReLU6(_Elementwise):
    """ReLU capped at 6: min(max(x, 0), 6) (reference ``nn/ReLU6.scala``)."""

    def _fn(self, x):
        return jnp.clip(x, 0.0, 6.0)


class LeakyReLU(_Elementwise):
    """ReLU with fixed negative slope ``negval`` (reference ``nn/LeakyReLU.scala``)."""

    def __init__(self, negval: float = 0.01, inplace: bool = False, name=None):
        super().__init__(name)
        self.negval = negval

    def _fn(self, x):
        return jnp.where(x >= 0, x, x * self.negval)


class ELU(_Elementwise):
    """Exponential linear unit (reference ``nn/ELU.scala``)."""

    def __init__(self, alpha: float = 1.0, inplace: bool = False, name=None):
        super().__init__(name)
        self.alpha = alpha

    def _fn(self, x):
        return jnp.where(x > 0, x, self.alpha * (jnp.exp(x) - 1.0))


class Tanh(_Elementwise):
    """Elementwise tanh (reference ``nn/Tanh.scala``)."""

    def _fn(self, x):
        return jnp.tanh(x)


class TanhShrink(_Elementwise):
    """x - tanh(x) (reference ``nn/TanhShrink.scala``)."""

    def _fn(self, x):
        return x - jnp.tanh(x)


class Sigmoid(_Elementwise):
    """Logistic sigmoid (reference ``nn/Sigmoid.scala``)."""

    def _fn(self, x):
        return jax.nn.sigmoid(x)


class LogSigmoid(_Elementwise):
    """log(sigmoid(x)), numerically stable (reference ``nn/LogSigmoid.scala``)."""

    def _fn(self, x):
        return jax.nn.log_sigmoid(x)


class SoftMax(_Elementwise):
    """Softmax over the last dim for 1-D/2-D input (torch semantics)."""

    layout_role = "opaque"     # axis-dependent, not pointwise

    def _fn(self, x):
        return jax.nn.softmax(x, axis=-1)


class SoftMin(_Elementwise):
    """Softmax of -x over the last dim (reference ``nn/SoftMin.scala``)."""

    layout_role = "opaque"

    def _fn(self, x):
        return jax.nn.softmax(-x, axis=-1)


class LogSoftMax(_Elementwise):
    """log-softmax over the last dim (reference ``nn/LogSoftMax.scala``)."""

    layout_role = "opaque"

    def _fn(self, x):
        return jax.nn.log_softmax(x, axis=-1)


class SoftPlus(_Elementwise):
    """Smooth ReLU log(1 + exp(beta*x))/beta (reference ``nn/SoftPlus.scala``)."""

    def __init__(self, beta: float = 1.0, name=None):
        super().__init__(name)
        self.beta = beta

    def _fn(self, x):
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(_Elementwise):
    """x / (1 + |x|) (reference ``nn/SoftSign.scala``)."""

    def _fn(self, x):
        return x / (1.0 + jnp.abs(x))


class SoftShrink(_Elementwise):
    """Shrink toward zero by ``lambd``; zero inside the band (reference ``nn/SoftShrinkage.scala``)."""

    def __init__(self, lambd: float = 0.5, name=None):
        super().__init__(name)
        self.lambd = lambd

    def _fn(self, x):
        return jnp.where(x > self.lambd, x - self.lambd,
                         jnp.where(x < -self.lambd, x + self.lambd, 0.0))


class HardShrink(_Elementwise):
    """Zero inside [-lambd, lambd], identity outside (reference ``nn/HardShrink.scala``)."""

    def __init__(self, lambd: float = 0.5, name=None):
        super().__init__(name)
        self.lambd = lambd

    def _fn(self, x):
        return jnp.where(jnp.abs(x) > self.lambd, x, 0.0)


class HardTanh(_Elementwise):
    """Clip to [min_value, max_value] (reference ``nn/HardTanh.scala``)."""

    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 inplace: bool = False, name=None):
        super().__init__(name)
        self.min_value, self.max_value = min_value, max_value

    def _fn(self, x):
        return jnp.clip(x, self.min_value, self.max_value)


class Clamp(HardTanh):
    """HardTanh with mandatory bounds (reference ``nn/Clamp.scala``)."""

    def __init__(self, min_value: float, max_value: float, name=None):
        super().__init__(min_value, max_value, name=name)


class Threshold(_Elementwise):
    """x if x > th else replacement value v (reference ``nn/Threshold.scala``)."""

    def __init__(self, th: float = 1e-6, v: float = 0.0,
                 ip: bool = False, name=None):
        super().__init__(name)
        self.th, self.v = th, v

    def _fn(self, x):
        return jnp.where(x > self.th, x, self.v)


class Power(_Elementwise):
    """(shift + scale * x) ** power (reference ``nn/Power.scala``)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0,
                 name=None):
        super().__init__(name)
        self.power, self.scale, self.shift = power, scale, shift

    def _fn(self, x):
        return jnp.power(self.shift + self.scale * x, self.power)


class Sqrt(_Elementwise):
    """Elementwise square root (reference ``nn/Sqrt.scala``)."""

    def _fn(self, x):
        return jnp.sqrt(x)


class Square(_Elementwise):
    """Elementwise square (reference ``nn/Square.scala``)."""

    def _fn(self, x):
        return x * x


class Abs(_Elementwise):
    """Elementwise absolute value (reference ``nn/Abs.scala``)."""

    def _fn(self, x):
        return jnp.abs(x)


class Log(_Elementwise):
    """Elementwise natural log (reference ``nn/Log.scala``)."""

    def _fn(self, x):
        return jnp.log(x)


class Exp(_Elementwise):
    """Elementwise exponential (reference ``nn/Exp.scala``)."""

    def _fn(self, x):
        return jnp.exp(x)


class Negative(_Elementwise):
    """Elementwise negation (reference ``nn/Negative.scala``)."""

    def _fn(self, x):
        return -x


class PReLU(Module):
    """ReLU with learnable negative slope (reference ``nn/PReLU.scala``).
    n_output_plane=0 -> one shared slope; else one per channel (dim 1)."""

    def __init__(self, n_output_plane: int = 0, init_weight=None, name=None):
        super().__init__(name)
        self.n_output_plane = n_output_plane
        self.init_weight = init_weight

    def _init_params(self, rng):
        if self.init_weight is not None:
            return {"weight": jnp.asarray(self.init_weight).reshape(-1)}
        n = max(1, self.n_output_plane)
        return {"weight": jnp.full((n,), 0.25)}

    def apply(self, params, input, state, training=False, rng=None):
        w = params["weight"]
        if self.n_output_plane > 0:
            # broadcast across channel dim: input (N, C, ...) or (C, ...)
            ch_axis = 1 if input.ndim > 3 or input.ndim == 2 else 0
            shape = [1] * input.ndim
            shape[ch_axis] = w.shape[0]
            w = jnp.reshape(w, shape)
        return jnp.where(input >= 0, input, input * w), state


class RReLU(Module):
    """Randomized leaky ReLU (reference ``nn/RReLU.scala``): slope ~
    U(lower, upper) during training, fixed mean slope at inference."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 inplace: bool = False, name=None):
        super().__init__(name)
        self.lower, self.upper = lower, upper

    def is_stochastic(self):
        return True

    def apply(self, params, input, state, training=False, rng=None):
        if training and rng is not None:
            slope = jax.random.uniform(rng, input.shape, input.dtype,
                                       self.lower, self.upper)
        else:
            slope = (self.lower + self.upper) / 2.0
        return jnp.where(input >= 0, input, input * slope), state


class Dropout(Module):
    """Inverted dropout (reference ``nn/Dropout.scala:44``)."""

    layout_role = "agnostic"

    def __init__(self, init_p: float = 0.5, inplace: bool = False,
                 scale: bool = True, name=None):
        super().__init__(name)
        self.p = init_p
        self.scale = scale

    def is_stochastic(self):
        return True

    def set_p(self, p: float):
        self.p = p
        self._jit_apply = None
        return self

    def apply(self, params, input, state, training=False, rng=None):
        if not training or self.p <= 0.0 or rng is None:
            return input, state
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, input.shape).astype(input.dtype)
        out = input * mask
        if self.scale:
            out = out / keep
        return out, state


class GaussianDropout(Module):
    """Multiplicative gaussian noise N(1, p/(1-p))."""

    layout_role = "agnostic"

    def __init__(self, rate: float, name=None):
        super().__init__(name)
        self.rate = rate

    def is_stochastic(self):
        return True

    def apply(self, params, input, state, training=False, rng=None):
        if not training or rng is None:
            return input, state
        stddev = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + stddev * jax.random.normal(rng, input.shape, input.dtype)
        return input * noise, state


class GaussianNoise(Module):
    """Additive gaussian noise (training only)."""

    layout_role = "agnostic"

    def __init__(self, stddev: float, name=None):
        super().__init__(name)
        self.stddev = stddev

    def is_stochastic(self):
        return True

    def apply(self, params, input, state, training=False, rng=None):
        if not training or rng is None:
            return input, state
        return input + self.stddev * jax.random.normal(rng, input.shape,
                                                       input.dtype), state


class L1Penalty(Module):
    """Identity forward; adds l1 sparsity gradient in backward
    (reference ``nn/L1Penalty.scala``).  Realised as a custom VJP so the same
    behavior falls out of whole-model ``jax.grad``."""

    def __init__(self, l1weight: float, size_average: bool = False,
                 provide_output: bool = True, name=None):
        super().__init__(name)
        self.l1weight = l1weight
        self.size_average = size_average

    def apply(self, params, input, state, training=False, rng=None):
        w = self.l1weight
        size_average = self.size_average

        @jax.custom_vjp
        def penalty(x):
            return x

        def fwd(x):
            return x, x

        def bwd(x, g):
            m = w / x.size if size_average else w
            return (g + m * jnp.sign(x),)

        penalty.defvjp(fwd, bwd)
        return (penalty(input) if training else input), state
