"""Linear / embedding family.

Reference: ``nn/Linear.scala``, ``nn/Bilinear.scala``, ``nn/LookupTable.scala:44``,
``nn/Add.scala``, ``nn/Mul.scala``, ``nn/CMul.scala``, ``nn/CAdd.scala``,
``nn/Euclidean.scala``, ``nn/Cosine.scala``.

Weight layouts are chosen for the MXU: Linear stores (in, out) so the forward
is a plain ``x @ w`` row-major matmul in one MXU pass (the reference stores
(out, in) and does gemv/gemm with a transpose, ``nn/Linear.scala``).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.analysis.contracts import ModuleContract
from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn import init as init_methods


class Linear(Module):
    """y = x W + b (reference ``nn/Linear.scala``).

    Tensor parallelism: tagging via ``parallel.column_parallel`` /
    ``row_parallel`` serves two execution styles.  On the GSPMD path the
    tag only picks the ``tp_specs`` sharding and XLA inserts the
    collectives.  Inside an EXPLICIT shard_map step (the pipeline x tp
    composition), :meth:`set_model_parallel` names the mesh axis and this
    module runs the Megatron split by hand: a column Linear emits
    feature-sharded output from the replicated input; a row Linear
    contracts its local rows and psums the pair's single all-reduce.
    The manual path engages only while the named axis is bound; ordinary
    forwards are untouched."""

    #: "column"/"row" Megatron tag; None = not tensor-parallel
    _tp = None
    #: float matmul input (any rank; the trailing dim contracts with W)
    contract = ModuleContract(dtypes="float")
    #: mesh-axis name for the explicit shard_map tp path
    model_parallel = None

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 w_regularizer=None, b_regularizer=None,
                 init_weight=None, init_bias=None, name=None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.init_weight = init_weight
        self.init_bias = init_bias
        self.weight_init_method = init_methods.RandomUniform()
        self.bias_init_method = init_methods.RandomUniform()

    def set_init_method(self, weight_init=None, bias_init=None):
        if weight_init is not None:
            self.weight_init_method = weight_init
        if bias_init is not None:
            self.bias_init_method = bias_init
        return self

    def _init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        fan_in, fan_out = self.input_size, self.output_size
        if self.init_weight is not None:
            w = jnp.asarray(self.init_weight)
            # native layout is (in, out); the reference's (out, in) is
            # accepted and transposed.  Square matrices are ambiguous and
            # assumed native.
            if (w.shape != (self.input_size, self.output_size) and
                    w.shape == (self.output_size, self.input_size)):
                w = w.T
        else:
            w = self.weight_init_method(k1, (self.input_size, self.output_size),
                                        fan_in, fan_out)
        p = {"weight": w}
        if self.with_bias:
            if self.init_bias is not None:
                p["bias"] = jnp.asarray(self.init_bias)
            else:
                p["bias"] = self.bias_init_method(k2, (self.output_size,),
                                                  fan_in, fan_out)
        return p

    def set_model_parallel(self, axis_name) -> "Linear":
        self.model_parallel = axis_name
        self._jit_apply = None
        return self

    def apply(self, params, input, state, training=False, rng=None):
        if self._tp and self.model_parallel:
            from bigdl_tpu.nn.attention import _axis_bound
            if _axis_bound(self.model_parallel):
                return self._apply_tp(params, input, state)
        out = input @ params["weight"]
        if self.with_bias:
            out = out + params["bias"]
        return out, state

    def _apply_tp(self, params, input, state):
        """Megatron split with explicit collectives (axis bound — inside
        the shard_map pipeline step; ``params`` leaves are the LOCAL
        shard).  No Megatron f/g custom-vjp operators: shard_map's
        transpose handles the replicated/split gradient accounting
        (grad-parity-tested against the unsplit stack)."""
        from jax import lax
        if self._tp == "column":
            out = input @ params["weight"]     # replicated in, sharded out
            if self.with_bias:
                out = out + params["bias"]     # column-sliced bias
            return out, state
        out = input @ params["weight"]         # partial: local rows only
        out = lax.psum(out, self.model_parallel)   # the pair's one psum
        if self.with_bias:
            out = out + params["bias"]         # full bias (replicated add)
        return out, state


class Bilinear(Module):
    """y_k = x1 W_k x2 + b_k over a Table input [x1, x2]
    (reference ``nn/Bilinear.scala``)."""

    def __init__(self, input_size1: int, input_size2: int, output_size: int,
                 bias_res: bool = True, w_regularizer=None, b_regularizer=None,
                 name=None):
        super().__init__(name)
        self.input_size1 = input_size1
        self.input_size2 = input_size2
        self.output_size = output_size
        self.bias_res = bias_res
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def _init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        stdv = 1.0 / math.sqrt(self.input_size1)
        w = jax.random.uniform(
            k1, (self.output_size, self.input_size1, self.input_size2),
            minval=-stdv, maxval=stdv)
        p = {"weight": w}
        if self.bias_res:
            p["bias"] = jax.random.uniform(k2, (self.output_size,),
                                           minval=-stdv, maxval=stdv)
        return p

    def apply(self, params, input, state, training=False, rng=None):
        x1, x2 = input[0], input[1]
        # (N,i1) x (o,i1,i2) x (N,i2) -> (N,o)
        out = jnp.einsum("ni,oij,nj->no", x1, params["weight"], x2)
        if self.bias_res:
            out = out + params["bias"]
        return out, state


class LookupTable(Module):
    """Embedding lookup (reference ``nn/LookupTable.scala:44``).

    Input indices are 1-based (Torch convention); optional max-norm
    renormalisation is applied to the gathered rows.
    """

    def __init__(self, n_index: int, n_output: int, padding_value: float = 0,
                 max_norm: float = float("inf"), norm_type: float = 2.0,
                 should_scale_grad_by_freq: bool = False,
                 w_regularizer=None, name=None):
        super().__init__(name)
        self.n_index = n_index
        self.n_output = n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        self.norm_type = norm_type
        self.w_regularizer = w_regularizer

    def _init_params(self, rng):
        return {"weight": jax.random.normal(rng, (self.n_index, self.n_output))}

    def apply(self, params, input, state, training=False, rng=None):
        idx = jnp.asarray(input).astype(jnp.int32) - 1  # 1-based -> 0-based
        idx = jnp.clip(idx, 0, self.n_index - 1)
        w = params["weight"]
        out = jnp.take(w, idx, axis=0)
        if self.max_norm != float("inf"):
            norms = jnp.linalg.norm(out, ord=self.norm_type, axis=-1, keepdims=True)
            scale = jnp.minimum(1.0, self.max_norm / jnp.maximum(norms, 1e-7))
            out = out * scale
        return out, state


class Add(Module):
    """Learnable per-element bias (reference ``nn/Add.scala``)."""

    def __init__(self, input_size: int, init_bias=None, name=None):
        super().__init__(name)
        self.input_size = input_size
        self.init_bias = init_bias

    def _init_params(self, rng):
        if self.init_bias is not None:
            return {"bias": jnp.asarray(self.init_bias).reshape(-1)}
        stdv = 1.0 / math.sqrt(self.input_size)
        return {"bias": jax.random.uniform(rng, (self.input_size,),
                                           minval=-stdv, maxval=stdv)}

    def apply(self, params, input, state, training=False, rng=None):
        return input + params["bias"], state


class Mul(Module):
    """Single learnable scalar gain (reference ``nn/Mul.scala``)."""

    def _init_params(self, rng):
        return {"weight": jax.random.uniform(rng, (), minval=-1.0, maxval=1.0)}

    def apply(self, params, input, state, training=False, rng=None):
        return input * params["weight"], state


class CMul(Module):
    """Learnable componentwise gain of given (broadcastable) size
    (reference ``nn/CMul.scala``)."""

    def __init__(self, size: Sequence[int], name=None):
        super().__init__(name)
        self.size = tuple(size)

    def _init_params(self, rng):
        n = 1
        for s in self.size:
            n *= s
        stdv = 1.0 / math.sqrt(n)
        return {"weight": jax.random.uniform(rng, self.size,
                                             minval=-stdv, maxval=stdv)}

    def apply(self, params, input, state, training=False, rng=None):
        return input * params["weight"], state


class CAdd(Module):
    """Learnable componentwise bias of given (broadcastable) size
    (reference ``nn/CAdd.scala``)."""

    def __init__(self, size: Sequence[int], name=None):
        super().__init__(name)
        self.size = tuple(size)

    def _init_params(self, rng):
        n = 1
        for s in self.size:
            n *= s
        stdv = 1.0 / math.sqrt(n)
        return {"bias": jax.random.uniform(rng, self.size,
                                           minval=-stdv, maxval=stdv)}

    def apply(self, params, input, state, training=False, rng=None):
        return input + params["bias"], state


class Euclidean(Module):
    """Output = distances to learnable centers (reference ``nn/Euclidean.scala``)."""

    def __init__(self, input_size: int, output_size: int, name=None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size

    def _init_params(self, rng):
        stdv = 1.0 / math.sqrt(self.input_size)
        return {"weight": jax.random.uniform(
            rng, (self.input_size, self.output_size), minval=-stdv, maxval=stdv)}

    def apply(self, params, input, state, training=False, rng=None):
        x = input[:, :, None] if input.ndim == 2 else input[:, None]
        d = x - params["weight"]
        out = jnp.sqrt(jnp.sum(d * d, axis=-2) + 1e-12)
        return out, state


class Cosine(Module):
    """Output = cosine similarity to learnable centers (reference ``nn/Cosine.scala``)."""

    def __init__(self, input_size: int, output_size: int, name=None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size

    def _init_params(self, rng):
        stdv = 1.0 / math.sqrt(self.input_size)
        return {"weight": jax.random.uniform(
            rng, (self.input_size, self.output_size), minval=-stdv, maxval=stdv)}

    def apply(self, params, input, state, training=False, rng=None):
        w = params["weight"]
        xn = input / jnp.maximum(jnp.linalg.norm(input, axis=-1, keepdims=True), 1e-12)
        wn = w / jnp.maximum(jnp.linalg.norm(w, axis=0, keepdims=True), 1e-12)
        return xn @ wn, state
