"""Channels-last (NHWC) compute path for convnets behind the NCHW facade.

The model zoo builds networks in Torch's NCHW convention, but the TPU's
native image layout is channels-last: convolutions/pooling/batch-norm with
``NCHW`` dimension numbers force XLA to wrap every such op in layout
transposes (and the small-taps matmul conv path in ``ops/convolution.py``
transposes explicitly).  :func:`to_channels_last` rewrites a built model so
the whole convolutional trunk computes in NHWC while the public API stays
NCHW: one :class:`NCHWToNHWC` at the network boundary, its inverse once at
the exit (or before the first interior layout-dependent module, e.g. the
``View`` flatten feeding the classifier head), and zero interior transposes
in between — a property the HLO-inspection test in ``tests/test_layout.py``
asserts on the jitted ResNet-50 forward.

The conversion walks the module tree using the layout contract every
:class:`~bigdl_tpu.nn.module.Module` declares (``layout_role`` — "opaque" /
"agnostic" / "spatial", see module.py) and the containers' structure:

- ``Sequential`` threads the current layout through its children, inserting
  the NCHW->NHWC switch right before the first spatial subtree and the
  inverse before any "opaque" (layout-dependent) child;
- ``Concat``/``ConcatTable`` fan the same layout into every branch and
  require the branches to agree on the output layout (a channel ``Concat``
  over NHWC maps is remapped from Torch dim 2 to the trailing axis);
- ``Graph`` propagates layouts along its topological order (``JoinTable``
  channel joins are remapped like ``Concat``);
- ``Remat`` is transparent.

Everything happens in place (params/state lists of already-initialised
containers are kept aligned with the inserted boundary modules), so a model
with loaded weights converts without re-initialisation: kernel storage is
HWIO in both layouts, only activations change shape.
"""

from __future__ import annotations

import jax.numpy as jnp

from bigdl_tpu.nn.module import Container, Module, Sequential
from bigdl_tpu.nn.graph import Graph
from bigdl_tpu.nn.structural import Remat
from bigdl_tpu.nn.table import Concat, ConcatTable, JoinTable

__all__ = ["NCHWToNHWC", "NHWCToNCHW", "to_channels_last", "apply_layout"]


class NCHWToNHWC(Module):
    """Boundary transpose: Torch-facade NCHW batch -> channels-last NHWC.

    Handles batched (N, C, H, W) and unbatched (C, H, W) activations.  A
    map whose spatial extent is 1x1 is moved with a reshape instead — the
    data is layout-identical, so the exit of a global-pool trunk costs
    nothing and no transpose op reaches the HLO."""

    def apply(self, params, input, state, training=False, rng=None):
        if input.ndim == 3:
            c, h, w = input.shape
            if h == 1 and w == 1:
                return jnp.reshape(input, (1, 1, c)), state
            return jnp.transpose(input, (1, 2, 0)), state
        n, c, h, w = input.shape
        if h == 1 and w == 1:
            return jnp.reshape(input, (n, 1, 1, c)), state
        return jnp.transpose(input, (0, 2, 3, 1)), state


class NHWCToNCHW(Module):
    """Boundary transpose: channels-last NHWC -> Torch-facade NCHW
    (reshape-only when the spatial extent is 1x1, see :class:`NCHWToNHWC`)."""

    def apply(self, params, input, state, training=False, rng=None):
        if input.ndim == 3:
            h, w, c = input.shape
            if h == 1 and w == 1:
                return jnp.reshape(input, (c, 1, 1)), state
            return jnp.transpose(input, (2, 0, 1)), state
        n, h, w, c = input.shape
        if h == 1 and w == 1:
            return jnp.reshape(input, (n, c, 1, 1)), state
        return jnp.transpose(input, (0, 3, 1, 2)), state


# ---------------------------------------------------------------------------
# structure editing helpers (keep params/state/grads lists aligned)
# ---------------------------------------------------------------------------

def _insert_child(seq: Container, i: int, module: Module) -> None:
    seq.children.insert(i, module)
    if seq._params is not None:
        module._ensure_init()          # boundary modules: {} params/state
        seq._params.insert(i, module._params)
        seq._state.insert(i, module._state)
        seq._grads.insert(i, module._grads)
    seq._jit_apply = None


def _wrapped(child: Module, before: Module = None,
             after: Module = None) -> Sequential:
    """A Sequential around ``child`` with optional boundary modules,
    inheriting ``child``'s initialised params/state so parent containers
    stay aligned after replacing the slot."""
    mods = [m for m in (before, child, after) if m is not None]
    w = Sequential()
    w.children.extend(mods)
    if child._params is not None:
        for m in mods:
            m._ensure_init()
        w._params = [m._params for m in mods]
        w._state = [m._state for m in mods]
        w._grads = [m._grads for m in mods]
    return w


def _replace_child(container: Container, i: int, wrapper: Module) -> None:
    container.children[i] = wrapper
    if container._params is not None:
        wrapper._ensure_init()
        container._params[i] = wrapper._params
        container._state[i] = wrapper._state
        container._grads[i] = wrapper._grads
    container._jit_apply = None


# ---------------------------------------------------------------------------
# layout analysis
# ---------------------------------------------------------------------------

def _supported_container(m: Module) -> bool:
    return isinstance(m, (Sequential, Concat, ConcatTable, Remat, Graph))


def _contains_spatial(m: Module) -> bool:
    if m.layout_role == "spatial":
        return True
    if isinstance(m, Container):
        return any(_contains_spatial(c) for c in m.children)
    return False


def _wants_nhwc(m: Module) -> bool:
    """True if ``m``'s INPUT edge consumes image maps (so the caller should
    hand it NHWC): the first non-agnostic thing along the input path is a
    spatial module."""
    if m.layout_role == "spatial":
        return True
    if isinstance(m, Sequential):
        for c in m.children:
            if c.layout_role == "agnostic":
                continue
            return _wants_nhwc(c)
        return False
    if isinstance(m, Remat):
        return bool(m.children) and _wants_nhwc(m.children[0])
    if isinstance(m, (Concat, ConcatTable)):
        return any(_wants_nhwc(c) for c in m.children)
    if isinstance(m, Graph):
        # graphs start at Input() placeholders; fall back to containment
        return _contains_spatial(m)
    return False


def _remap_channel_concat(m, out_layout: str) -> None:
    """Torch channel concat is dim 2 (axis 1, NCHW); in NHWC the channel is
    the trailing axis.  dimension = -1 resolves to the last axis at any
    rank, so unbatched 3-D activations keep working."""
    if out_layout != "NHWC":
        return
    if m.dimension == 2:
        m.dimension = -1
    elif m.dimension != -1:   # != -1: not already converted
        raise ValueError(
            f"{m.name}: only channel concatenation (dimension=2) is "
            f"supported on the channels-last path, got dimension="
            f"{m.dimension}")


# ---------------------------------------------------------------------------
# the converter
# ---------------------------------------------------------------------------

def _convert(m: Module, fmt: str) -> str:
    """Convert ``m`` in place to consume activations in ``fmt``; returns the
    layout of its output."""
    if isinstance(m, NCHWToNHWC):
        return "NHWC"
    if isinstance(m, NHWCToNCHW):
        return "NCHW"
    if isinstance(m, Sequential):
        return _convert_sequential(m, fmt)
    if isinstance(m, Remat):
        if not m.children:
            return fmt
        c = m.children[0]
        if (fmt == "NHWC" and c.layout_role == "opaque" and
                not _supported_container(c) and
                not isinstance(c, (NCHWToNHWC, NHWCToNCHW))):
            _replace_child(m, 0, _wrapped(c, before=NHWCToNCHW()))
            return "NCHW"
        return _convert(c, fmt)
    if isinstance(m, Graph):
        return _convert_graph(m, fmt)
    if isinstance(m, (Concat, ConcatTable)):
        return _convert_branch(m, fmt)
    if m.layout_role == "agnostic":
        return fmt
    if m.layout_role == "spatial":
        m.set_format(fmt)
        return fmt
    # opaque leaf or unsupported container: the CALLER must have already
    # restored NCHW in front of it
    return "NCHW"


def _convert_sequential(seq: Sequential, fmt: str) -> str:
    cur = fmt
    i = 0
    while i < len(seq.children):
        c = seq.children[i]
        if isinstance(c, NCHWToNHWC):
            cur = "NHWC"
        elif isinstance(c, NHWCToNCHW):
            cur = "NCHW"
        elif c.layout_role == "agnostic":
            pass
        elif (c.layout_role == "spatial" or
              (_supported_container(c) and _wants_nhwc(c))):
            if cur == "NCHW":
                # the single entry switch, placed before the first spatial
                # subtree
                _insert_child(seq, i, NCHWToNHWC())
                i += 1
                cur = "NHWC"
            cur = _convert(c, cur)
        elif _supported_container(c):
            cur = _convert(c, cur)
        else:
            # layout-dependent module (View/Reshape/Linear/...): restore
            # the NCHW facade once, right before it
            if cur == "NHWC":
                _insert_child(seq, i, NHWCToNCHW())
                i += 1
                cur = "NCHW"
        i += 1
    return cur


def _convert_branch(cc, fmt: str) -> str:
    outs = []
    for i in range(len(cc.children)):
        c = cc.children[i]
        if isinstance(c, (NCHWToNHWC, NHWCToNCHW)) or c.layout_role in (
                "agnostic", "spatial") or _supported_container(c):
            outs.append(_convert(c, fmt))
        else:                      # opaque branch head needs the facade back
            if fmt == "NHWC":
                _replace_child(cc, i, _wrapped(c, before=NHWCToNCHW()))
            outs.append("NCHW")
    if len(set(outs)) > 1:
        raise ValueError(
            f"{cc.name}: branches disagree on output layout {outs}; "
            f"restructure so every branch ends in the same layout")
    out = outs[0] if outs else fmt
    if isinstance(cc, Concat):
        _remap_channel_concat(cc, out)
    return out


def _convert_graph(g: Graph, fmt: str) -> str:
    layouts = {}
    for idx, node in enumerate(g.executions):
        if not node.prev:
            in_l = fmt
        else:
            ins = {layouts[id(p)] for p in node.prev}
            if len(ins) > 1:
                raise ValueError(
                    f"{g.name}: node {node.element.name} receives mixed "
                    f"layouts {sorted(ins)}")
            (in_l,) = ins
        el = node.element
        if isinstance(el, JoinTable):
            _remap_channel_concat(el, in_l)
            out = in_l
        elif (el.layout_role in ("agnostic", "spatial") or
              _supported_container(el) or
              isinstance(el, (NCHWToNHWC, NHWCToNCHW))):
            out = _convert(el, in_l)
        else:
            if in_l == "NHWC":
                wrapper = _wrapped(el, before=NHWCToNCHW())
                node.element = wrapper
                _replace_child(g, idx, wrapper)
            out = "NCHW"
        layouts[id(node)] = out
    outl = {layouts[id(n)] for n in g.output_nodes}
    if len(outl) > 1:
        raise ValueError(f"{g.name}: output nodes disagree on layout")
    return outl.pop()


def _clear_jit(model: Module) -> None:
    model.clear_jit_cache()


def to_channels_last(model: Module) -> Module:
    """Rewrite ``model`` so its convolutional trunk computes in NHWC while
    the public API keeps consuming/producing Torch-style NCHW activations.

    In place and idempotent; safe on initialised models (loaded weights are
    untouched — kernels are stored HWIO in both layouts).  Returns the
    converted model: the SAME object for ``Sequential`` tops, a wrapping
    ``Sequential`` for other containers whose output stays a spatial map.
    """
    if not isinstance(model, Container) or not _contains_spatial(model):
        return model
    if not isinstance(model, Sequential):
        model = _wrapped(model)
    if model._params is not None:
        # re-link child param/state views to the top-level lists first: a
        # clone_module'd tree holds per-container COPIES (pickling breaks
        # the sharing), and the in-place inserts below must land in the
        # one tree apply() reads
        model._adopt()
    out = _convert_sequential(model, "NCHW")
    if out == "NHWC":
        _insert_child(model, len(model.children), NHWCToNCHW())
    if model._params is not None and isinstance(model, Container):
        model._adopt()
    _clear_jit(model)
    return model


def apply_layout(model: Module, layout: str) -> Module:
    """Zoo-builder helper: ``layout="NHWC"`` converts to the channels-last
    compute path (the default), ``"NCHW"`` keeps the classic layout."""
    if layout == "NHWC":
        return to_channels_last(model)
    if layout == "NCHW":
        return model
    raise ValueError(f"unknown layout {layout!r}: expected 'NHWC' or 'NCHW'")
