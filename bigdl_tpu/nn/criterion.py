"""Loss functions (criterions).

Reference: the 24 criterion files in ``nn/`` (SURVEY §2.6) —
ClassNLLCriterion.scala, CrossEntropyCriterion.scala, MSECriterion.scala, ...

Conventions kept from the reference/Torch: class labels are 1-based floats;
``size_average=True`` divides by batch size (or element count where Torch
does); each criterion is a pure ``apply(input, target) -> scalar``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Criterion


def _to_index(target):
    return jnp.asarray(target).astype(jnp.int32) - 1  # 1-based -> 0-based


class ClassNLLCriterion(Criterion):
    """NLL over log-probabilities, 1-based integer targets
    (reference ``nn/ClassNLLCriterion.scala``)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def apply(self, input, target):
        logp = jnp.atleast_2d(input)
        idx = jnp.ravel(_to_index(target))
        picked = jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]
        if self.weights is not None:
            w = jnp.take(self.weights, idx)
            loss = -jnp.sum(picked * w)
            denom = jnp.sum(w)
        else:
            loss = -jnp.sum(picked)
            denom = logp.shape[0]
        return loss / denom if self.size_average else loss


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused (reference ``nn/CrossEntropyCriterion.scala``)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.nll = ClassNLLCriterion(weights, size_average)

    def apply(self, input, target):
        return self.nll.apply(jax.nn.log_softmax(input, axis=-1), target)


class MSECriterion(Criterion):
    def apply(self, input, target):
        d = input - target
        s = jnp.sum(d * d)
        return s / input.size if self.size_average else s


class AbsCriterion(Criterion):
    def apply(self, input, target):
        s = jnp.sum(jnp.abs(input - target))
        return s / input.size if self.size_average else s


class BCECriterion(Criterion):
    """Binary cross entropy on probabilities (reference ``nn/BCECriterion.scala``)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def apply(self, input, target):
        eps = 1e-12
        x = jnp.clip(input, eps, 1.0 - eps)
        l = -(target * jnp.log(x) + (1.0 - target) * jnp.log(1.0 - x))
        if self.weights is not None:
            l = l * self.weights
        s = jnp.sum(l)
        return s / input.size if self.size_average else s


class DistKLDivCriterion(Criterion):
    """KL(target || input) with input = log-probs
    (reference ``nn/DistKLDivCriterion.scala``)."""

    def apply(self, input, target):
        l = jnp.where(target > 0, target * (jnp.log(jnp.maximum(target, 1e-30))
                                            - input), 0.0)
        s = jnp.sum(l)
        # sizeAverage divides by element count, not batch
        # (reference DistKLDivCriterion.scala: sum / input.nElement())
        return s / input.size if self.size_average else s


class CosineEmbeddingCriterion(Criterion):
    """Input Table [x1, x2], target +-1 (reference ``nn/CosineEmbeddingCriterion.scala``)."""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        x1, x2 = input[0], input[1]
        y = jnp.ravel(jnp.asarray(target))
        x1 = jnp.atleast_2d(x1)
        x2 = jnp.atleast_2d(x2)
        cos = jnp.sum(x1 * x2, axis=-1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
        l = jnp.where(y > 0, 1.0 - cos, jnp.maximum(0.0, cos - self.margin))
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class HingeEmbeddingCriterion(Criterion):
    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        l = jnp.where(target > 0, input,
                      jnp.maximum(0.0, self.margin - input))
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class L1HingeEmbeddingCriterion(Criterion):
    """Input Table [x1, x2]; L1 distance hinge
    (reference ``nn/L1HingeEmbeddingCriterion.scala``)."""

    def __init__(self, margin: float = 1.0):
        super().__init__()
        self.margin = margin

    def apply(self, input, target):
        d = jnp.sum(jnp.abs(input[0] - input[1]))
        y = jnp.ravel(jnp.asarray(target))[0]
        return jnp.where(y > 0, d, jnp.maximum(0.0, self.margin - d))


class MarginCriterion(Criterion):
    """Hinge loss max(0, margin - y*x) (reference ``nn/MarginCriterion.scala``)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        l = jnp.maximum(0.0, self.margin - input * target)
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class MarginRankingCriterion(Criterion):
    """Input Table [x1, x2]: max(0, -y*(x1-x2) + margin)
    (reference ``nn/MarginRankingCriterion.scala``)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        x1, x2 = jnp.ravel(input[0]), jnp.ravel(input[1])
        y = jnp.ravel(jnp.asarray(target))
        l = jnp.maximum(0.0, -y * (x1 - x2) + self.margin)
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class MultiCriterion(Criterion):
    """Weighted sum of criterions on the same (input, target)
    (reference ``nn/MultiCriterion.scala``)."""

    def __init__(self):
        super().__init__()
        self.criterions: List[Criterion] = []
        self.weights: List[float] = []

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def apply(self, input, target):
        return sum(w * c.apply(input, target)
                   for c, w in zip(self.criterions, self.weights))


class ParallelCriterion(Criterion):
    """i-th criterion on i-th (input, target) table entries
    (reference ``nn/ParallelCriterion.scala``)."""

    def __init__(self, repeat_target: bool = False):
        super().__init__()
        self.repeat_target = repeat_target
        self.criterions: List[Criterion] = []
        self.weights: List[float] = []

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def apply(self, input, target):
        total = 0.0
        for i, (c, w) in enumerate(zip(self.criterions, self.weights)):
            t = target if self.repeat_target else target[i]
            total = total + w * c.apply(input[i], t)
        return total


class MultiLabelMarginCriterion(Criterion):
    """Multi-class multi-label hinge (reference ``nn/MultiLabelMarginCriterion.scala``).
    target rows list 1-based label indices, 0-padded."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        x = jnp.atleast_2d(input)
        t = jnp.atleast_2d(jnp.asarray(target)).astype(jnp.int32)
        n, c = x.shape

        def per_sample(xi, ti):
            valid = ti > 0
            idx = jnp.clip(ti - 1, 0, c - 1)
            is_target = jnp.zeros((c,), bool).at[idx].set(valid)
            tscores = jnp.where(valid, xi[idx], jnp.inf)  # (c,) padded
            # for every (target j, class k not in targets): max(0, 1 - (x_j - x_k))
            margins = jnp.maximum(0.0, 1.0 - (tscores[:, None] - xi[None, :]))
            mask = valid[:, None] & (~is_target)[None, :]
            return jnp.sum(jnp.where(mask, margins, 0.0)) / c

        l = jax.vmap(per_sample)(x, t)
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class MultiLabelSoftMarginCriterion(Criterion):
    """Multi-label one-vs-all BCE-with-logits
    (reference ``nn/MultiLabelSoftMarginCriterion.scala``)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def apply(self, input, target):
        l = target * jax.nn.log_sigmoid(input) \
            + (1.0 - target) * jax.nn.log_sigmoid(-input)
        if self.weights is not None:
            l = l * self.weights
        n_classes = input.shape[-1]
        s = -jnp.sum(l) / n_classes
        n = input.shape[0] if input.ndim > 1 else 1
        return s / n if self.size_average else s


class MultiMarginCriterion(Criterion):
    """Multi-class hinge (reference ``nn/MultiMarginCriterion.scala``)."""

    def __init__(self, p: int = 1, weights=None, margin: float = 1.0,
                 size_average: bool = True):
        super().__init__()
        self.p = p
        self.weights = None if weights is None else jnp.asarray(weights)
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        x = jnp.atleast_2d(input)
        idx = jnp.ravel(_to_index(target))
        n, c = x.shape
        tgt_score = jnp.take_along_axis(x, idx[:, None], axis=1)
        margins = jnp.maximum(0.0, self.margin - tgt_score + x) ** self.p
        if self.weights is not None:
            margins = margins * jnp.take(self.weights, idx)[:, None]
        onehot = jax.nn.one_hot(idx, c, dtype=bool)
        l = jnp.sum(jnp.where(onehot, 0.0, margins), axis=1) / c
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class SmoothL1Criterion(Criterion):
    def apply(self, input, target):
        d = jnp.abs(input - target)
        l = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        s = jnp.sum(l)
        return s / input.size if self.size_average else s


class SmoothL1CriterionWithWeights(Criterion):
    """Smooth-L1 with inside/outside weights, Fast-RCNN style
    (reference ``nn/SmoothL1CriterionWithWeights.scala``)."""

    def __init__(self, sigma: float = 1.0, num: int = 0):
        super().__init__()
        self.sigma2 = sigma * sigma
        self.num = num

    def apply(self, input, target):
        if isinstance(target, (list, tuple)):
            t, inw, outw = target[0], target[1], target[2]
        else:
            t, inw, outw = target, 1.0, 1.0
        d = (input - t) * inw
        ad = jnp.abs(d)
        l = jnp.where(ad < 1.0 / self.sigma2,
                      0.5 * self.sigma2 * d * d,
                      ad - 0.5 / self.sigma2)
        s = jnp.sum(l * outw)
        return s / self.num if self.num > 0 else s


class SoftmaxWithCriterion(Criterion):
    """Caffe SoftmaxWithLoss over (N, C, H, W) logits with spatial 1-based
    labels (reference ``nn/SoftmaxWithCriterion.scala``)."""

    def __init__(self, ignore_label: Optional[int] = None,
                 normalize_mode: str = "VALID"):
        super().__init__()
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode

    def apply(self, input, target):
        logp = jax.nn.log_softmax(input, axis=1)
        idx = _to_index(target)  # (N, H, W) or (N, 1, H, W)
        if idx.ndim == input.ndim:
            idx = idx[:, 0]
        picked = jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]
        if self.ignore_label is not None:
            valid = (idx + 1) != self.ignore_label
            picked = jnp.where(valid, picked, 0.0)
            count = jnp.sum(valid)
        else:
            count = picked.size
        loss = -jnp.sum(picked)
        if self.normalize_mode == "VALID":
            return loss / jnp.maximum(count, 1)
        if self.normalize_mode == "BATCH_SIZE":
            return loss / input.shape[0]
        if self.normalize_mode == "FULL":
            return loss / picked.size
        return loss


class SoftMarginCriterion(Criterion):
    """log(1 + exp(-y*x)) (reference ``nn/SoftMarginCriterion.scala``)."""

    def apply(self, input, target):
        l = jnp.log1p(jnp.exp(-input * target))
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class L1Cost(Criterion):
    """Sum of absolute values of the input (reference ``nn/L1Cost.scala``)."""

    def apply(self, input, target=None):
        return jnp.sum(jnp.abs(input))


class CosineDistanceCriterion(Criterion):
    """1 - cosine(input, target) (reference ``nn/CosineDistanceCriterion.scala``)."""

    def apply(self, input, target):
        x = jnp.atleast_2d(input)
        t = jnp.atleast_2d(target)
        cos = jnp.sum(x * t, axis=-1) / jnp.maximum(
            jnp.linalg.norm(x, axis=-1) * jnp.linalg.norm(t, axis=-1), 1e-12)
        l = 1.0 - cos
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class DiceCoefficientCriterion(Criterion):
    """1 - dice overlap (reference ``nn/DiceCoefficientCriterion.scala``)."""

    def __init__(self, size_average: bool = True, epsilon: float = 1.0):
        super().__init__()
        self.size_average = size_average
        self.epsilon = epsilon

    def apply(self, input, target):
        x = jnp.atleast_2d(input)
        t = jnp.atleast_2d(target)
        inter = jnp.sum(x * t, axis=-1)
        union = jnp.sum(x, axis=-1) + jnp.sum(t, axis=-1)
        l = 1.0 - 2.0 * (inter + self.epsilon) / (union + 2 * self.epsilon)
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class ClassSimplexCriterion(Criterion):
    """MSE against a regular-simplex embedding of the target class
    (reference ``nn/ClassSimplexCriterion.scala``)."""

    def __init__(self, n_classes: int):
        super().__init__()
        self.n_classes = n_classes
        self.simplex = jnp.asarray(self._build_simplex(n_classes))

    @staticmethod
    def _build_simplex(n: int) -> np.ndarray:
        m = np.zeros((n, n), np.float32)
        m[0, 0] = 1.0
        for k in range(1, n):
            s = float(m[k - 1, :k - 1] @ m[k - 1, :k - 1]) if k > 1 else 0.0
            # regular simplex construction (Gram-Schmidt style)
        # simpler closed form: vertices of a regular simplex in R^n
        m = np.eye(n, dtype=np.float32)
        centroid = m.mean(axis=0, keepdims=True)
        m = m - centroid
        m = m / np.linalg.norm(m, axis=1, keepdims=True)
        return m

    def apply(self, input, target):
        idx = jnp.ravel(_to_index(target))
        t = jnp.take(self.simplex, idx, axis=0)
        d = jnp.atleast_2d(input) - t
        return jnp.mean(jnp.sum(d * d, axis=-1))


class TimeDistributedCriterion(Criterion):
    """Apply a criterion at every timestep of (N, T, ...) input
    (reference ``nn/TimeDistributedCriterion.scala``).

    Separable inner criterions (unweighted ClassNLL/CrossEntropy, MSE, Abs,
    BCE) take a vectorized path: one criterion call on the time-flattened
    batch instead of T unrolled calls — identical value (each per-timestep
    mean over N equals the flat mean over N*T scaled by T), but the jitted
    graph stays O(1) in sequence length instead of O(T)."""

    def __init__(self, critrn: Criterion, size_average: bool = False):
        super().__init__()
        self.critrn = critrn
        self.size_average = size_average

    def _separable(self) -> bool:
        c = self.critrn
        if isinstance(c, (MSECriterion, AbsCriterion)):
            return True
        if isinstance(c, (ClassNLLCriterion, BCECriterion)):
            return c.weights is None
        if isinstance(c, CrossEntropyCriterion):
            return c.nll.weights is None
        return False

    def _inner_size_average(self) -> bool:
        c = self.critrn
        # CrossEntropy delegates to its NLL: the ctor arg lives there, not
        # on the base-class default
        if isinstance(c, CrossEntropyCriterion):
            return c.nll.size_average
        return getattr(c, "size_average", False)

    def apply(self, input, target):
        t_steps = input.shape[1]
        if self._separable():
            flat_in = jnp.reshape(input, (-1,) + input.shape[2:])
            flat_tgt = jnp.reshape(jnp.asarray(target),
                                   (-1,) + jnp.asarray(target).shape[2:])
            total = self.critrn.apply(flat_in, flat_tgt)
            # flat size_average divides by N*T (or N*T*D); the unrolled sum
            # of per-timestep means divides by N (or N*D) — scale back
            if self._inner_size_average():
                total = total * t_steps
        else:
            total = 0.0
            for t in range(t_steps):
                total = total + self.critrn.apply(input[:, t], target[:, t])
        return total / t_steps if self.size_average else total
