"""bigdl_tpu.nn — the layer zoo.

Mirrors the reference's ``com.intel.analytics.bigdl.nn`` package surface
(SURVEY §2.6) with a pure-function core per layer.
"""

from bigdl_tpu.nn.module import (Module, Container, Sequential, Criterion,
                                 Activity)
from bigdl_tpu.nn import init
from bigdl_tpu.nn.init import (InitializationMethod, Zeros, Ones,
                               ConstInitMethod, RandomUniform, RandomNormal,
                               Xavier, MsraFiller, BilinearFiller)
from bigdl_tpu.nn.linear import (Linear, Bilinear, LookupTable, Add, Mul,
                                 CMul, CAdd, Euclidean, Cosine)
from bigdl_tpu.nn.conv import (SpatialConvolution, SpatialShareConvolution,
                               SpatialDilatedConvolution,
                               SpatialFullConvolution, TemporalConvolution,
                               VolumetricConvolution,
                               VolumetricFullConvolution,
                               SpatialConvolutionMap)
from bigdl_tpu.nn.pooling import (SpatialMaxPooling, SpatialAveragePooling,
                                  VolumetricMaxPooling, RoiPooling)
from bigdl_tpu.ops.nms import Nms, nms_mask
from bigdl_tpu.nn.attention import (MultiHeadAttention,
                                    scaled_dot_product_attention)
from bigdl_tpu.nn.moe import MixtureOfExperts
from bigdl_tpu.nn.tf_ops import (Const, Fill, Shape, SplitAndSelect,
                                 StrideSlice)
from bigdl_tpu.nn.activation import (ReLU, ReLU6, LeakyReLU, ELU, PReLU,
                                     RReLU, Tanh, TanhShrink, Sigmoid,
                                     LogSigmoid, SoftMax, SoftMin, LogSoftMax,
                                     SoftPlus, SoftSign, SoftShrink,
                                     HardShrink, HardTanh, Clamp, Threshold,
                                     Power, Sqrt, Square, Abs, Log, Exp,
                                     Negative, Dropout, GaussianDropout,
                                     GaussianNoise, L1Penalty)
from bigdl_tpu.nn.normalization import (BatchNormalization,
                                        SpatialBatchNormalization,
                                        SpatialCrossMapLRN,
                                        SpatialWithinChannelLRN,
                                        SpatialContrastiveNormalization,
                                        SpatialDivisiveNormalization,
                                        SpatialSubtractiveNormalization,
                                        Normalize)
from bigdl_tpu.nn.structural import (Identity, Echo, Contiguous, Reshape,
                                     View, InferReshape, Squeeze, Unsqueeze,
                                     Transpose, Narrow, Select, Index,
                                     MaskedSelect, Max, Min, Mean, Sum,
                                     Replicate, Padding, SpatialZeroPadding,
                                     GradientReversal, Scale, Bottle, Remat,
                                     MM, MV,
                                     DotProduct, Pack, Reverse,
                                     MulConstant, AddConstant,
                                     ChannelNormalize, DeviceAugment)
from bigdl_tpu.nn.table import (Concat, ConcatTable, ParallelTable, MapTable,
                                JoinTable, SplitTable, SelectTable,
                                NarrowTable, FlattenTable, MixtureTable,
                                CAddTable, CSubTable, CMulTable, CDivTable,
                                CMaxTable, CMinTable, PairwiseDistance,
                                CosineDistance)
from bigdl_tpu.nn.criterion import (ClassNLLCriterion, CrossEntropyCriterion,
                                    MSECriterion, AbsCriterion, BCECriterion,
                                    DistKLDivCriterion,
                                    CosineEmbeddingCriterion,
                                    HingeEmbeddingCriterion,
                                    L1HingeEmbeddingCriterion,
                                    MarginCriterion, MarginRankingCriterion,
                                    MultiCriterion, ParallelCriterion,
                                    MultiLabelMarginCriterion,
                                    MultiLabelSoftMarginCriterion,
                                    MultiMarginCriterion, SmoothL1Criterion,
                                    SmoothL1CriterionWithWeights,
                                    SoftmaxWithCriterion, SoftMarginCriterion,
                                    L1Cost, CosineDistanceCriterion,
                                    DiceCoefficientCriterion,
                                    ClassSimplexCriterion,
                                    TimeDistributedCriterion)
from bigdl_tpu.nn.graph import Graph, ModuleNode, Input
from bigdl_tpu.nn.layout import (NCHWToNHWC, NHWCToNCHW, to_channels_last,
                                 apply_layout)
from bigdl_tpu.nn.fuse import fold_conv_bn
from bigdl_tpu.nn.recurrent import (Cell, RnnCell, LSTM, LSTMPeephole, GRU,
                                    ConvLSTMPeephole, ConvLSTMPeephole3D,
                                    Recurrent, BiRecurrent, TimeDistributed,
                                    BinaryTreeLSTM, TreeLSTM)
