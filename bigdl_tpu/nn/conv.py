"""Convolution layers.

Reference: ``nn/SpatialConvolution.scala:42`` (im2col+gemm with per-frame
threading), ``nn/SpatialShareConvolution.scala:29``,
``nn/SpatialDilatedConvolution.scala``, ``nn/SpatialFullConvolution.scala``,
``nn/TemporalConvolution.scala:49``, ``nn/VolumetricConvolution.scala``,
``nn/VolumetricFullConvolution.scala``, ``nn/SpatialConvolutionMap.scala``.

BigDL argument order is (kernelW, kernelH, strideW, strideH, padW, padH);
arrays are (..., H, W), so the (W, H) pairs are swapped once at the
constructor edge.  pad = -1 means SAME padding (reference convention).
Kernels are stored HWIO; activations default NCHW with an optional
``format="NHWC"`` for the TPU-preferred layout.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.analysis.contracts import ModuleContract
from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn import init as init_methods
from bigdl_tpu import ops


class SpatialConvolution(Module):
    """2-D convolution (reference ``nn/SpatialConvolution.scala:42``)."""

    layout_role = "spatial"
    #: image maps in, float compute (bigdl_tpu.analysis contract checker)
    contract = ModuleContract(input_ndim=(3, 4), dtypes="float")

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 n_group: int = 1, propagate_back: bool = True,
                 w_regularizer=None, b_regularizer=None,
                 init_weight=None, init_bias=None,
                 with_bias: bool = True, format: str = "NCHW", name=None):
        super().__init__(name)
        assert n_input_plane % n_group == 0, \
            "Number of input channels should be multiples of group."
        assert n_output_plane % n_group == 0, \
            "Number of output channels should be multiples of group."
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.n_group = n_group
        self.propagate_back = propagate_back
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.init_weight = init_weight
        self.init_bias = init_bias
        self.with_bias = with_bias
        self.format = format
        self.weight_init_method = init_methods.RandomUniform()
        self.bias_init_method = init_methods.RandomUniform()

    def set_init_method(self, weight_init=None, bias_init=None):
        if weight_init is not None:
            self.weight_init_method = weight_init
        if bias_init is not None:
            self.bias_init_method = bias_init
        return self

    @property
    def _fans(self):
        fan_in = (self.n_input_plane // self.n_group) * self.kernel_h * self.kernel_w
        fan_out = (self.n_output_plane // self.n_group) * self.kernel_h * self.kernel_w
        return fan_in, fan_out

    def _init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        fan_in, fan_out = self._fans
        shape = (self.kernel_h, self.kernel_w,
                 self.n_input_plane // self.n_group, self.n_output_plane)
        if self.init_weight is not None:
            w = jnp.asarray(self.init_weight)
            if w.shape != shape:
                # accept reference (group, out/g, in/g, kh, kw) layout
                w = jnp.reshape(w, (self.n_group,
                                    self.n_output_plane // self.n_group,
                                    self.n_input_plane // self.n_group,
                                    self.kernel_h, self.kernel_w))
                w = jnp.transpose(w, (3, 4, 2, 0, 1)).reshape(shape)
        else:
            w = self.weight_init_method(k1, shape, fan_in, fan_out)
        p = {"weight": w}
        if self.with_bias:
            if self.init_bias is not None:
                p["bias"] = jnp.asarray(self.init_bias)
            else:
                p["bias"] = self.bias_init_method(k2, (self.n_output_plane,),
                                                  fan_in, fan_out)
        return p

    def _padding(self):
        if self.pad_w == -1 or self.pad_h == -1:
            return "SAME"
        return (self.pad_h, self.pad_w)

    def apply(self, params, input, state, training=False, rng=None):
        squeeze = input.ndim == 3
        if squeeze:
            input = input[None]
        out = ops.conv2d(input, params["weight"],
                         params.get("bias") if self.with_bias else None,
                         stride=(self.stride_h, self.stride_w),
                         padding=self._padding(),
                         groups=self.n_group, format=self.format)
        if squeeze:
            out = out[0]
        return out, state


class SpatialShareConvolution(SpatialConvolution):
    """Buffer-sharing variant in the reference
    (``nn/SpatialShareConvolution.scala:29``); on TPU there are no im2col
    buffers to share, so this is semantically identical to SpatialConvolution."""


class SpatialDilatedConvolution(Module):
    """Atrous 2-D convolution (reference ``nn/SpatialDilatedConvolution.scala``)."""

    layout_role = "spatial"

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 dilation_w: int = 1, dilation_h: int = 1,
                 w_regularizer=None, b_regularizer=None,
                 format: str = "NCHW", name=None):
        super().__init__(name)
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.dilation_w, self.dilation_h = dilation_w, dilation_h
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.format = format

    def _init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        fan_in = self.n_input_plane * self.kh * self.kw
        stdv = 1.0 / math.sqrt(fan_in)
        w = jax.random.uniform(k1, (self.kh, self.kw, self.n_input_plane,
                                    self.n_output_plane),
                               minval=-stdv, maxval=stdv)
        b = jax.random.uniform(k2, (self.n_output_plane,), minval=-stdv, maxval=stdv)
        return {"weight": w, "bias": b}

    def apply(self, params, input, state, training=False, rng=None):
        squeeze = input.ndim == 3
        if squeeze:
            input = input[None]
        out = ops.conv2d(input, params["weight"], params["bias"],
                         stride=(self.dh, self.dw),
                         padding=(self.pad_h, self.pad_w),
                         dilation=(self.dilation_h, self.dilation_w),
                         format=self.format)
        if squeeze:
            out = out[0]
        return out, state


class SpatialFullConvolution(Module):
    """Transposed (fractionally-strided) convolution, a.k.a. deconvolution
    (reference ``nn/SpatialFullConvolution.scala``)."""

    layout_role = "spatial"

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 adj_w: int = 0, adj_h: int = 0,
                 n_group: int = 1, no_bias: bool = False,
                 w_regularizer=None, b_regularizer=None,
                 format: str = "NCHW", name=None):
        super().__init__(name)
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.adj_w, self.adj_h = adj_w, adj_h
        self.n_group = n_group
        self.no_bias = no_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.format = format
        self.weight_init_method = init_methods.RandomUniform()
        self.bias_init_method = init_methods.RandomUniform()

    def set_init_method(self, weight_init=None, bias_init=None):
        if weight_init is not None:
            self.weight_init_method = weight_init
        if bias_init is not None:
            self.bias_init_method = bias_init
        return self

    def _init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        fan_in = self.n_input_plane * self.kh * self.kw
        shape = (self.kh, self.kw, self.n_input_plane, self.n_output_plane)
        w = self.weight_init_method(k1, shape, fan_in, fan_in)
        p = {"weight": w}
        if not self.no_bias:
            p["bias"] = self.bias_init_method(k2, (self.n_output_plane,),
                                              fan_in, fan_in)
        return p

    def apply(self, params, input, state, training=False, rng=None):
        squeeze = input.ndim == 3
        if squeeze:
            input = input[None]
        out = ops.conv_transpose2d(
            input, params["weight"],
            None if self.no_bias else params.get("bias"),
            stride=(self.dh, self.dw), padding=(self.pad_h, self.pad_w),
            adj=(self.adj_h, self.adj_w), format=self.format)
        if squeeze:
            out = out[0]
        return out, state


class TemporalConvolution(Module):
    """1-D convolution over (N, T, C) sequences
    (reference ``nn/TemporalConvolution.scala:49``)."""

    def __init__(self, input_frame_size: int, output_frame_size: int,
                 kernel_w: int, stride_w: int = 1,
                 propagate_back: bool = True,
                 w_regularizer=None, b_regularizer=None, name=None):
        super().__init__(name)
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.propagate_back = propagate_back
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.weight_init_method = init_methods.RandomUniform()
        self.bias_init_method = init_methods.RandomUniform()

    def set_init_method(self, weight_init=None, bias_init=None):
        if weight_init is not None:
            self.weight_init_method = weight_init
        if bias_init is not None:
            self.bias_init_method = bias_init
        return self

    def _init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        fan_in = self.input_frame_size * self.kernel_w
        w = self.weight_init_method(
            k1, (self.kernel_w, self.input_frame_size, self.output_frame_size),
            fan_in, self.output_frame_size * self.kernel_w)
        b = self.bias_init_method(k2, (self.output_frame_size,), fan_in, fan_in)
        return {"weight": w, "bias": b}

    def apply(self, params, input, state, training=False, rng=None):
        squeeze = input.ndim == 2
        if squeeze:
            input = input[None]
        out = ops.temporal_conv1d(input, params["weight"], params["bias"],
                                  stride=self.stride_w)
        if squeeze:
            out = out[0]
        return out, state


class VolumetricConvolution(Module):
    """3-D convolution over (N, C, D, H, W)
    (reference ``nn/VolumetricConvolution.scala``)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int,
                 d_t: int = 1, d_w: int = 1, d_h: int = 1,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 with_bias: bool = True, name=None):
        super().__init__(name)
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.k_t, self.k_w, self.k_h = k_t, k_w, k_h
        self.d_t, self.d_w, self.d_h = d_t, d_w, d_h
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.with_bias = with_bias

    def _init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        fan_in = self.n_input_plane * self.k_t * self.k_h * self.k_w
        stdv = 1.0 / math.sqrt(fan_in)
        w = jax.random.uniform(
            k1, (self.k_t, self.k_h, self.k_w, self.n_input_plane,
                 self.n_output_plane), minval=-stdv, maxval=stdv)
        p = {"weight": w}
        if self.with_bias:
            p["bias"] = jax.random.uniform(k2, (self.n_output_plane,),
                                           minval=-stdv, maxval=stdv)
        return p

    def apply(self, params, input, state, training=False, rng=None):
        squeeze = input.ndim == 4
        if squeeze:
            input = input[None]
        out = ops.conv3d(input, params["weight"],
                         params.get("bias") if self.with_bias else None,
                         stride=(self.d_t, self.d_h, self.d_w),
                         padding=(self.pad_t, self.pad_h, self.pad_w))
        if squeeze:
            out = out[0]
        return out, state


class VolumetricFullConvolution(Module):
    """Transposed 3-D convolution (reference ``nn/VolumetricFullConvolution.scala``)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int,
                 d_t: int = 1, d_w: int = 1, d_h: int = 1,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 adj_t: int = 0, adj_w: int = 0, adj_h: int = 0,
                 no_bias: bool = False, name=None):
        super().__init__(name)
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.k_t, self.k_w, self.k_h = k_t, k_w, k_h
        self.d_t, self.d_w, self.d_h = d_t, d_w, d_h
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.adj_t, self.adj_w, self.adj_h = adj_t, adj_w, adj_h
        self.no_bias = no_bias

    def _init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        fan_in = self.n_input_plane * self.k_t * self.k_h * self.k_w
        stdv = 1.0 / math.sqrt(fan_in)
        w = jax.random.uniform(
            k1, (self.k_t, self.k_h, self.k_w, self.n_input_plane,
                 self.n_output_plane), minval=-stdv, maxval=stdv)
        p = {"weight": w}
        if not self.no_bias:
            p["bias"] = jax.random.uniform(k2, (self.n_output_plane,),
                                           minval=-stdv, maxval=stdv)
        return p

    def apply(self, params, input, state, training=False, rng=None):
        from bigdl_tpu.ops.convolution import conv_transpose3d
        squeeze = input.ndim == 4
        if squeeze:
            input = input[None]
        out = conv_transpose3d(input, params["weight"],
                               None if self.no_bias else params.get("bias"),
                               stride=(self.d_t, self.d_h, self.d_w),
                               padding=(self.pad_t, self.pad_h, self.pad_w),
                               adj=(self.adj_t, self.adj_h, self.adj_w))
        if squeeze:
            out = out[0]
        return out, state


class SpatialConvolutionMap(Module):
    """Convolution with an explicit input-plane -> output-plane connection
    table (reference ``nn/SpatialConvolutionMap.scala``).  Expressed as a
    dense convolution with a fixed binary mask over the kernel."""

    def __init__(self, conn_table, kw: int, kh: int,
                 dw: int = 1, dh: int = 1, pad_w: int = 0, pad_h: int = 0,
                 name=None):
        super().__init__(name)
        self.conn_table = jnp.asarray(conn_table, jnp.int32)  # (K, 2) 1-based
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.n_input_plane = int(self.conn_table[:, 0].max())
        self.n_output_plane = int(self.conn_table[:, 1].max())

    @staticmethod
    def full(nin: int, nout: int):
        import numpy as np
        t = [[i + 1, o + 1] for o in range(nout) for i in range(nin)]
        return np.asarray(t)

    @staticmethod
    def one_to_one(n: int):
        import numpy as np
        return np.asarray([[i + 1, i + 1] for i in range(n)])

    def _init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        n_conn = self.conn_table.shape[0]
        fan_in = self.kh * self.kw * n_conn // max(1, self.n_output_plane)
        stdv = 1.0 / math.sqrt(fan_in * 1.0)
        w = jax.random.uniform(
            k1, (self.kh, self.kw, self.n_input_plane, self.n_output_plane),
            minval=-stdv, maxval=stdv)
        b = jax.random.uniform(k2, (self.n_output_plane,), minval=-stdv,
                               maxval=stdv)
        return {"weight": w, "bias": b}

    def _mask(self):
        import numpy as np
        m = np.zeros((1, 1, self.n_input_plane, self.n_output_plane), np.float32)
        ct = np.asarray(self.conn_table)
        m[0, 0, ct[:, 0] - 1, ct[:, 1] - 1] = 1.0
        return jnp.asarray(m)

    def apply(self, params, input, state, training=False, rng=None):
        squeeze = input.ndim == 3
        if squeeze:
            input = input[None]
        w = params["weight"] * self._mask()
        out = ops.conv2d(input, w, params["bias"],
                         stride=(self.dh, self.dw),
                         padding=(self.pad_h, self.pad_w))
        if squeeze:
            out = out[0]
        return out, state
