"""Graph container: DAG of modules built with the ``inputs()`` DSL.

Reference: ``nn/Graph.scala:58`` (topo-sorted executions, per-node input
marshalling, reverse-order backward) and ``utils/DirectedGraph.scala:34``.

Because every module is a pure function here, Graph.apply is just a
topological fold — XLA sees one fused program; there is no per-node backward
bookkeeping (``jax.vjp`` of the whole fold replaces ``nn/Graph.scala:87-120``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

from bigdl_tpu.nn.module import Module, Container, _child_rng


class ModuleNode:
    """A node wrapping a module, with predecessor edges
    (reference ``utils/Node`` + ``AbstractModule.inputs:539``)."""

    def __init__(self, element: Module):
        self.element = element
        self.prev: List["ModuleNode"] = []
        self.next: List["ModuleNode"] = []

    def inputs(self, *nodes) -> "ModuleNode":
        for n in nodes:
            if isinstance(n, Module):
                n = ModuleNode(n)
            self.prev.append(n)
            n.next.append(self)
        return self

    def __repr__(self):
        return f"Node({self.element.name})"


class Graph(Container):
    """DAG container (reference ``nn/Graph.scala:58``).

    ``Graph(inputs, outputs)``: inputs is a node or list of nodes fed with
    the graph's input activity (in order); outputs likewise gathered.
    """

    def __init__(self, inputs, outputs, name=None):
        super().__init__(name)
        self.input_nodes = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self.output_nodes = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        self.executions = self._topo_sort()
        for node in self.executions:
            self.add(node.element)
        self._node_index = {id(n): i for i, n in enumerate(self.executions)}

    def _topo_sort(self) -> List[ModuleNode]:
        # collect all nodes reachable (backwards) from outputs
        seen: Dict[int, ModuleNode] = {}
        stack = list(self.output_nodes)
        while stack:
            n = stack.pop()
            if id(n) in seen:
                continue
            seen[id(n)] = n
            stack.extend(n.prev)
        # Kahn's algorithm over the reachable subgraph
        indeg = {i: sum(1 for p in n.prev if id(p) in seen)
                 for i, n in seen.items()}
        ready = [n for i, n in seen.items() if indeg[i] == 0]
        order: List[ModuleNode] = []
        while ready:
            n = ready.pop(0)
            order.append(n)
            for nxt in n.next:
                if id(nxt) in seen:
                    indeg[id(nxt)] -= 1
                    if indeg[id(nxt)] == 0:
                        ready.append(nxt)
        if len(order) != len(seen):
            raise ValueError("Graph contains a cycle")
        return order

    def apply(self, params, input, state, training=False, rng=None):
        is_multi = isinstance(input, (list, tuple)) and len(self.input_nodes) > 1
        outputs: Dict[int, object] = {}
        new_states = list(state)
        for i, node in enumerate(self.executions):
            if not node.prev:
                # source node: feed from graph input
                k = self.input_nodes.index(node) if node in self.input_nodes else 0
                x = input[k] if is_multi else input
            elif len(node.prev) == 1:
                x = outputs[id(node.prev[0])]
            else:
                x = [outputs[id(p)] for p in node.prev]
            y, s = node.element.apply(params[i], x, state[i],
                                      training=training, rng=_child_rng(rng, i))
            outputs[id(node)] = y
            new_states[i] = s
        outs = [outputs[id(n)] for n in self.output_nodes]
        return (outs[0] if len(outs) == 1 else outs), new_states


def Input():
    """Placeholder source node (reference ``nn/Input.scala``)."""
    from bigdl_tpu.nn.structural import Identity
    return ModuleNode(Identity())
