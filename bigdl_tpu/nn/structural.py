"""Structural / tensor-manipulation layers.

Reference: Reshape.scala, View.scala, InferReshape.scala, Squeeze.scala,
Unsqueeze.scala, Transpose.scala, Contiguous.scala, Identity.scala, Echo.scala,
Narrow.scala, Select.scala, Index.scala, MaskedSelect.scala, Max.scala,
Min.scala, Mean.scala, Sum.scala, Replicate.scala, Padding.scala,
SpatialZeroPadding.scala, GradientReversal.scala, Scale.scala, Bottle.scala,
MM.scala, MV.scala, DotProduct.scala, Pack.scala, Reverse.scala.

Dimension arguments are 1-based (Torch convention), as in the reference.
Many layers take ``n_input_dims``: when the actual input has one more dim,
it is treated as a batch dim and the op shifts right by one.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Container, Module, _child_rng


def _axis(dim_1based: int, ndim: int, n_input_dims: int = -1) -> int:
    """Convert a 1-based (possibly batch-relative) dim to a 0-based axis."""
    d = dim_1based
    if d < 0:
        return ndim + d
    ax = d - 1
    if n_input_dims > 0 and ndim == n_input_dims + 1:
        ax += 1
    return ax


class Identity(Module):
    """Pass input through unchanged (reference ``nn/Identity.scala``)."""

    layout_role = "agnostic"

    def apply(self, params, input, state, training=False, rng=None):
        return input, state


class Echo(Module):
    """Identity that prints its input shape (debug aid, reference ``nn/Echo.scala``)."""

    layout_role = "agnostic"

    def apply(self, params, input, state, training=False, rng=None):
        jax.debug.print("Echo {name}: shape {shape}", name=self.name,
                        shape=jnp.asarray(input.shape))
        return input, state


class Contiguous(Module):
    """No-op on XLA arrays (kept for API parity, reference ``nn/Contiguous.scala``)."""

    layout_role = "agnostic"

    def apply(self, params, input, state, training=False, rng=None):
        return input, state


class Reshape(Module):
    """Reshape non-batch dims to ``size`` (reference ``nn/Reshape.scala``).

    batch_mode None (default): auto — treat first dim as batch when the
    element count of the remaining dims matches prod(size).
    """

    def __init__(self, size: Sequence[int], batch_mode: Optional[bool] = None,
                 name=None):
        super().__init__(name)
        self.size = tuple(int(s) for s in size)
        self.batch_mode = batch_mode

    def apply(self, params, input, state, training=False, rng=None):
        n = int(np.prod(self.size))
        total = int(np.prod(input.shape))
        if self.batch_mode is True or (
                self.batch_mode is None and total != n and
                input.shape and total == n * input.shape[0]):
            return jnp.reshape(input, (input.shape[0],) + self.size), state
        return jnp.reshape(input, self.size), state


class View(Module):
    """Reshape with -1 inference (reference ``nn/View.scala``)."""

    def __init__(self, *sizes, name=None):
        super().__init__(name)
        if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
            sizes = tuple(sizes[0])
        self.sizes = tuple(int(s) for s in sizes)
        self.num_input_dims = 0

    def set_num_input_dims(self, n: int):
        self.num_input_dims = n
        return self

    def apply(self, params, input, state, training=False, rng=None):
        sizes = self.sizes
        if self.num_input_dims > 0 and input.ndim > self.num_input_dims:
            batch = input.shape[:input.ndim - self.num_input_dims]
            return jnp.reshape(input, batch + sizes), state
        n = int(np.prod([s for s in sizes if s != -1]))
        if (-1 not in sizes and input.ndim > len(sizes)
                and int(np.prod(input.shape[1:])) == n):
            return jnp.reshape(input, (input.shape[0],) + sizes), state
        return jnp.reshape(input, sizes), state


class InferReshape(Module):
    """Reshape where 0 copies the input dim and -1 is inferred
    (reference ``nn/InferReshape.scala``)."""

    def __init__(self, size: Sequence[int], batch_mode: bool = False, name=None):
        super().__init__(name)
        self.size = tuple(int(s) for s in size)
        self.batch_mode = batch_mode

    def apply(self, params, input, state, training=False, rng=None):
        in_shape = input.shape[1:] if self.batch_mode else input.shape
        out = []
        for i, s in enumerate(self.size):
            out.append(in_shape[i] if s == 0 else s)
        if self.batch_mode:
            out = [input.shape[0]] + out
        return jnp.reshape(input, tuple(out)), state


class Squeeze(Module):
    """Drop size-1 dims (1-based ``dim``, reference ``nn/Squeeze.scala``)."""

    def __init__(self, dim: Optional[int] = None, num_input_dims: int = -1,
                 name=None):
        super().__init__(name)
        self.dim = dim
        self.num_input_dims = num_input_dims

    def apply(self, params, input, state, training=False, rng=None):
        if self.dim is None:
            return jnp.squeeze(input), state
        ax = _axis(self.dim, input.ndim, self.num_input_dims)
        if input.shape[ax] != 1:
            return input, state
        return jnp.squeeze(input, axis=ax), state


class Unsqueeze(Module):
    """Insert a size-1 dim at 1-based ``pos`` (reference ``nn/Unsqueeze.scala``)."""

    def __init__(self, pos: int, num_input_dims: int = -1, name=None):
        super().__init__(name)
        self.pos = pos
        self.num_input_dims = num_input_dims

    def apply(self, params, input, state, training=False, rng=None):
        ax = self.pos - 1
        if self.num_input_dims > 0 and input.ndim == self.num_input_dims + 1:
            ax += 1
        return jnp.expand_dims(input, ax), state


class Transpose(Module):
    """Swap listed (1-based) dim pairs in order (reference ``nn/Transpose.scala``)."""

    def __init__(self, permutations: Sequence[Sequence[int]], name=None):
        super().__init__(name)
        self.permutations = [tuple(p) for p in permutations]

    def apply(self, params, input, state, training=False, rng=None):
        x = input
        for d1, d2 in self.permutations:
            x = jnp.swapaxes(x, d1 - 1, d2 - 1)
        return x, state


class Narrow(Module):
    """Slice length elements from 1-based offset along dim
    (reference ``nn/Narrow.scala``)."""

    def __init__(self, dimension: int, offset: int, length: int = 1, name=None):
        super().__init__(name)
        self.dimension = dimension
        self.offset = offset
        self.length = length

    def apply(self, params, input, state, training=False, rng=None):
        ax = _axis(self.dimension, input.ndim)
        length = self.length
        if length < 0:
            length = input.shape[ax] - self.offset + 1 + length + 1
        start = self.offset - 1
        idx = [slice(None)] * input.ndim
        idx[ax] = slice(start, start + length)
        return input[tuple(idx)], state


class Select(Module):
    """Select 1-based index along 1-based dim, dropping the dim
    (reference ``nn/Select.scala``)."""

    def __init__(self, dimension: int, index: int, name=None):
        super().__init__(name)
        self.dimension = dimension
        self.index = index

    def apply(self, params, input, state, training=False, rng=None):
        ax = _axis(self.dimension, input.ndim)
        i = self.index - 1 if self.index > 0 else input.shape[ax] + self.index
        return jnp.take(input, i, axis=ax), state


class Index(Module):
    """Table input [tensor, indices]: gather along dim (1-based indices)
    (reference ``nn/Index.scala``)."""

    def __init__(self, dimension: int, name=None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, input, state, training=False, rng=None):
        x, idx = input[0], input[1]
        ax = self.dimension - 1
        return jnp.take(x, idx.astype(jnp.int32) - 1, axis=ax), state


class MaskedSelect(Module):
    """Table input [tensor, mask] -> masked elements.

    XLA needs static shapes, so unlike the reference
    (``nn/MaskedSelect.scala``) the output keeps the input length with
    non-selected positions zeroed, packed to the front.
    """

    def apply(self, params, input, state, training=False, rng=None):
        x, mask = input[0], input[1]
        flat = jnp.ravel(x)
        m = jnp.ravel(mask).astype(bool)
        order = jnp.argsort(~m, stable=True)
        packed = jnp.where(m[order], flat[order], 0.0)
        return packed, state


class Max(Module):
    """Max over a 1-based dim (reference ``nn/Max.scala``)."""

    def __init__(self, dim: int = 1, num_input_dims: int = -1, name=None):
        super().__init__(name)
        self.dim = dim
        self.num_input_dims = num_input_dims

    def apply(self, params, input, state, training=False, rng=None):
        ax = _axis(self.dim, input.ndim, self.num_input_dims)
        return jnp.max(input, axis=ax), state


class Min(Module):
    """Min over a 1-based dim (reference ``nn/Min.scala``)."""

    def __init__(self, dim: int = 1, num_input_dims: int = -1, name=None):
        super().__init__(name)
        self.dim = dim
        self.num_input_dims = num_input_dims

    def apply(self, params, input, state, training=False, rng=None):
        ax = _axis(self.dim, input.ndim, self.num_input_dims)
        return jnp.min(input, axis=ax), state


class Mean(Module):
    """Mean over a 1-based dim (reference ``nn/Mean.scala``)."""

    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 squeeze: bool = True, name=None):
        super().__init__(name)
        self.dimension = dimension
        self.n_input_dims = n_input_dims
        self.squeeze = squeeze

    def apply(self, params, input, state, training=False, rng=None):
        ax = _axis(self.dimension, input.ndim, self.n_input_dims)
        return jnp.mean(input, axis=ax, keepdims=not self.squeeze), state


class Sum(Module):
    """Sum over a 1-based dim (reference ``nn/Sum.scala``)."""

    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 size_average: bool = False, squeeze: bool = True, name=None):
        super().__init__(name)
        self.dimension = dimension
        self.n_input_dims = n_input_dims
        self.size_average = size_average
        self.squeeze = squeeze

    def apply(self, params, input, state, training=False, rng=None):
        ax = _axis(self.dimension, input.ndim, self.n_input_dims)
        if self.size_average:
            out = jnp.mean(input, axis=ax, keepdims=not self.squeeze)
        else:
            out = jnp.sum(input, axis=ax, keepdims=not self.squeeze)
        return out, state


class Replicate(Module):
    """Insert a new dim of size n_features at 1-based dim
    (reference ``nn/Replicate.scala``)."""

    def __init__(self, n_features: int, dim: int = 1, n_dim: int = -1, name=None):
        super().__init__(name)
        self.n_features = n_features
        self.dim = dim
        self.n_dim = n_dim

    def apply(self, params, input, state, training=False, rng=None):
        ax = self.dim - 1
        if self.n_dim > 0 and input.ndim == self.n_dim + 1:
            ax += 1
        x = jnp.expand_dims(input, ax)
        reps = [1] * x.ndim
        reps[ax] = self.n_features
        return jnp.tile(x, reps), state


class Padding(Module):
    """Pad ``pad`` entries (negative -> before, positive -> after) along dim
    with ``value`` (reference ``nn/Padding.scala``)."""

    def __init__(self, dim: int, pad: int, n_input_dim: int,
                 value: float = 0.0, n_index: int = 1, name=None):
        super().__init__(name)
        self.dim = dim
        self.pad = pad
        self.n_input_dim = n_input_dim
        self.value = value

    def apply(self, params, input, state, training=False, rng=None):
        ax = _axis(self.dim, input.ndim, self.n_input_dim)
        pads = [(0, 0)] * input.ndim
        pads[ax] = (-self.pad, 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(input, pads, constant_values=self.value), state


class SpatialZeroPadding(Module):
    """Zero-pad (or crop, negative) NCHW spatial borders (reference
    ``nn/SpatialZeroPadding.scala`` — its negative pads ``narrow`` the
    input; ``lax.pad``'s negative edge config is the same operation)."""

    def __init__(self, pad_left: int, pad_right: int = None,
                 pad_top: int = None, pad_bottom: int = None, name=None):
        super().__init__(name)
        self.pl = pad_left
        self.pr = pad_right if pad_right is not None else pad_left
        self.pt = pad_top if pad_top is not None else pad_left
        self.pb = pad_bottom if pad_bottom is not None else pad_left

    def apply(self, params, input, state, training=False, rng=None):
        if (input.shape[-1] + self.pl + self.pr < 1 or
                input.shape[-2] + self.pt + self.pb < 1):
            raise ValueError("input is too small")
        cfg = ([(0, 0, 0)] * (input.ndim - 2) +
               [(self.pt, self.pb, 0), (self.pl, self.pr, 0)])
        zero = jnp.asarray(0, input.dtype)
        return jax.lax.pad(input, zero, cfg), state


class GradientReversal(Module):
    """Identity forward, -lambda * grad backward (reference
    ``nn/GradientReversal.scala``), via custom VJP."""

    layout_role = "agnostic"

    def __init__(self, the_lambda: float = 1.0, name=None):
        super().__init__(name)
        self.the_lambda = the_lambda

    def apply(self, params, input, state, training=False, rng=None):
        lam = self.the_lambda

        @jax.custom_vjp
        def rev(x):
            return x

        rev.defvjp(lambda x: (x, None), lambda _, g: (-lam * g,))
        return rev(input), state


class Scale(Module):
    """cmul + cadd with learnable size-shaped weight and bias
    (reference ``nn/Scale.scala``)."""

    def __init__(self, size: Sequence[int], init_weight=None,
                 init_bias=None, name=None):
        super().__init__(name)
        self.size = tuple(size)
        self.init_weight = init_weight
        self.init_bias = init_bias

    def _init_params(self, rng):
        w = (jnp.asarray(self.init_weight).reshape(self.size)
             if self.init_weight is not None else jnp.ones(self.size))
        b = (jnp.asarray(self.init_bias).reshape(self.size)
             if self.init_bias is not None else jnp.zeros(self.size))
        return {"weight": w, "bias": b}

    def apply(self, params, input, state, training=False, rng=None):
        w, b = params["weight"], params["bias"]
        shape = [1] * input.ndim
        # align size to dims starting at axis 1 (channel-wise for NCHW)
        for i, s in enumerate(self.size):
            shape[min(i + 1, input.ndim - 1)] = s
        return input * jnp.reshape(w, shape) + jnp.reshape(b, shape), state


class Bottle(Module):
    """Flatten leading dims, apply inner module, restore
    (reference ``nn/Bottle.scala``)."""

    def __init__(self, module: Module, n_input_dim: int = 2,
                 n_output_dim: int = 2, name=None):
        super().__init__(name)
        self.module = module
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim

    def _init_params(self, rng):
        return [self.module._init_params(rng)]

    def _init_state(self):
        return [self.module._init_state()]

    def modules(self):
        return [self] + self.module.modules()

    def apply(self, params, input, state, training=False, rng=None):
        lead = input.shape[:input.ndim - self.n_input_dim + 1]
        rest = input.shape[input.ndim - self.n_input_dim + 1:]
        flat = jnp.reshape(input, (-1,) + rest)
        out, s = self.module.apply(params[0], flat, state[0],
                                   training=training, rng=rng)
        out = jnp.reshape(out, lead + out.shape[1:])
        return out, [s]


class Remat(Container):
    """Activation-checkpoint (rematerialization) wrapper.

    ``jax.checkpoint`` around the wrapped module's pure ``apply``: the
    backward pass recomputes the module's internal activations from the
    module INPUT instead of storing them through the whole forward —
    trading one extra forward's FLOPs per wrapped span for O(spans)
    instead of O(all ops) activation residency.  This is the standard
    TPU lever for pushing a deep transformer stack past the HBM capacity
    wall (no reference equivalent: the reference keeps every layer's
    ``output``/``gradInput`` buffer resident by design,
    ``nn/abstractnn/AbstractModule.scala:54``).

    ``policy`` selects what intermediates MAY be saved anyway:

    - ``None`` / ``"nothing"`` — save nothing inside the span (max memory
      savings, full forward recompute in the VJP);
    - ``"dots"`` — save matmul/contraction outputs
      (``jax.checkpoint_policies.dots_with_no_batch_dims_saveable``):
      only cheap elementwise/norm ops recompute, a good default when the
      span is matmul-dominated;
    - ``"save_attn"`` — save ONLY tensors tagged ``attn_ctx``
      (:class:`~bigdl_tpu.nn.attention.MultiHeadAttention` names its
      attention context): one O(B*T*d) residual per block keeps the
      attention kernel (flash/chunked/standard) out of the VJP's
      recompute while projections/elementwise still remat — the
      middle ground where ``"dots"`` exceeds HBM but full recompute
      wastes the most expensive op;
    - any ``jax.checkpoint_policies`` callable.

    Implemented as a Container with one child so ``modules()`` walks,
    ``parallel.tp_specs``'s spec recursion, sequence-parallel wiring and
    child param adoption all see through it transparently.
    """

    def __init__(self, inner: Module, policy=None, name=None):
        super().__init__(name)
        self.add(inner)
        self.policy = policy
        self.checkpoint_policy()   # typo'd policies fail HERE, not at trace

    def add(self, module: Module) -> "Container":
        if self.children:
            raise ValueError("Remat wraps exactly one module; compose a "
                             "Sequential inside it instead")
        return super().add(module)

    def checkpoint_policy(self):
        if callable(self.policy):
            return self.policy
        if self.policy in (None, "nothing"):
            return None
        if self.policy == "dots":
            return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        if self.policy == "save_attn":
            return jax.checkpoint_policies.save_only_these_names("attn_ctx")
        raise ValueError(
            f"unknown remat policy {self.policy!r}: expected None, "
            "'nothing', 'dots', 'save_attn', or a jax.checkpoint_policies "
            "callable")

    def apply(self, params, input, state, training=False, rng=None):
        inner = self.children[0]

        def fn(p, x, s, r):
            return inner.apply(p, x, s, training=training, rng=r)

        out, new_s = jax.checkpoint(fn, policy=self.checkpoint_policy())(
            params[0], input, state[0], _child_rng(rng, 0))
        return out, [new_s]


class MM(Module):
    """Matrix multiply of a Table [a, b] (reference ``nn/MM.scala``)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False, name=None):
        super().__init__(name)
        self.trans_a, self.trans_b = trans_a, trans_b

    def apply(self, params, input, state, training=False, rng=None):
        a, b = input[0], input[1]
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b), state


class MV(Module):
    """Matrix-vector multiply of a Table [m, v] (reference ``nn/MV.scala``)."""

    def __init__(self, trans: bool = False, name=None):
        super().__init__(name)
        self.trans = trans

    def apply(self, params, input, state, training=False, rng=None):
        m, v = input[0], input[1]
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v), state


class DotProduct(Module):
    """Row-wise dot product of a Table [a, b] (reference ``nn/DotProduct.scala``)."""

    def apply(self, params, input, state, training=False, rng=None):
        a, b = input[0], input[1]
        return jnp.sum(a * b, axis=-1), state


class Pack(Module):
    """Stack a Table of tensors along a new 1-based dim (reference ``nn/Pack.scala``)."""

    def __init__(self, dimension: int, name=None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, input, state, training=False, rng=None):
        xs = input if isinstance(input, (list, tuple)) else [input]
        return jnp.stack(list(xs), axis=self.dimension - 1), state


class Reverse(Module):
    """Reverse along a 1-based dim (reference ``nn/Reverse.scala``)."""

    def __init__(self, dimension: int = 1, name=None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, input, state, training=False, rng=None):
        return jnp.flip(input, axis=self.dimension - 1), state


class MulConstant(Module):
    """Multiply by a scalar constant (reference ``nn/MulConstant.scala``)."""

    layout_role = "agnostic"

    def __init__(self, constant_scalar: float, inplace: bool = False, name=None):
        super().__init__(name)
        self.constant = constant_scalar

    def apply(self, params, input, state, training=False, rng=None):
        return input * self.constant, state


class ChannelNormalize(Module):
    """Device-side per-channel input normalization for NCHW batches:
    ``(x.float() - mean[c]) / std[c]``, optionally cast to ``dtype``.

    TPU-first ingest companion to the host-side ``BGRImgNormalizer``
    (reference ``BGRImgNormalizer.scala`` always normalizes on CPU):
    putting this module first lets the data pipeline ship RAW uint8
    pixels over the host->device link — a 4x byte reduction on any
    deployment, and the deciding factor on links where bandwidth is the
    ingest wall (measured on the tunneled v5e: post-execution transfer
    bandwidth ~40 MB/s makes the float32 batch upload the whole story).
    The subtraction/scale fuses into the first convolution under XLA.
    ``dtype`` pins the output precision (e.g. ``"bfloat16"`` under
    mixed-precision training, where a float32 output would silently
    promote the first conv back to fp32).  ``format="NHWC"`` normalizes
    the trailing channel axis for the channels-last compute path."""

    layout_role = "spatial"

    def __init__(self, mean, std, dtype=None, format="NCHW", name=None):
        super().__init__(name)
        self.mean = tuple(float(m) for m in mean)
        self.std = tuple(float(s) for s in std)
        self.dtype = dtype
        self.format = format

    def apply(self, params, input, state, training=False, rng=None):
        c = len(self.mean)
        if self.format == "NCHW":
            shape = (1, c) + (1,) * (input.ndim - 2)
        else:
            shape = (1,) * (input.ndim - 1) + (c,)
        mean = jnp.asarray(self.mean, jnp.float32).reshape(shape)
        std = jnp.asarray(self.std, jnp.float32).reshape(shape)
        out = (input.astype(jnp.float32) - mean) / std
        if self.dtype is not None:
            out = out.astype(self.dtype)
        return out, state


class DeviceAugment(Module):
    """On-device crop/flip(/ColorJitter) head for device-augment ingest
    (ISSUE 16): consumes the ``[frames_u8_NHWC, offsets_i32, flips_u8]``
    (optionally ``+ [jitter_seeds_i32]``) input list that
    ``StreamingIngest`` packs in ``deviceAugment`` mode and emits the
    uint8 NCHW crop batch the host MT path would have produced — the
    per-pixel crop/flip/transpose work moves off the decode threads and
    into the fused step, so only raw full frames plus a few bytes of
    ride-along metadata cross the host->device link.  Place it first,
    ahead of ``ChannelNormalize``.  The crop offsets and flip flags are
    host-drawn from the clone-and-commit RNG stream, so trained weights
    are bit-identical to the host path (asserted in
    test_prefetch_determinism.py).  ``color_jitter`` is a dict of
    ``brightness``/``contrast``/``saturation`` factors; it requires the
    packer's ride-along seeds and breaks host-path parity by design."""

    layout_role = "spatial"

    def __init__(self, crop_h, crop_w, color_jitter=None, name=None):
        super().__init__(name)
        self.crop_h = int(crop_h)
        self.crop_w = int(crop_w)
        self.color_jitter = dict(color_jitter) if color_jitter else None

    def apply(self, params, input, state, training=False, rng=None):
        from bigdl_tpu.dataset import device_augment as _aug
        if not isinstance(input, (list, tuple)) or len(input) < 3:
            # Already-assembled NCHW batch (host path): pass through so
            # one model definition serves both ingest modes.
            return input, state
        frames, offsets, flips = input[0], input[1], input[2]
        out = _aug.crop_flip_transpose(frames, offsets, flips,
                                       self.crop_h, self.crop_w)
        if self.color_jitter and len(input) > 3:
            out = _aug.color_jitter(out, input[3], **self.color_jitter)
        return out, state


class AddConstant(Module):
    """Add a scalar constant (reference ``nn/AddConstant.scala``)."""

    layout_role = "agnostic"

    def __init__(self, constant_scalar: float, inplace: bool = False, name=None):
        super().__init__(name)
        self.constant = constant_scalar

    def apply(self, params, input, state, training=False, rng=None):
        return input + self.constant, state
