"""Mixture-of-experts layer (Switch-style top-1 routing).

The reference has no MoE (its ``MixtureTable`` is a dense gated blend over
a Table of expert outputs, ``nn/MixtureTable.scala`` — every expert runs on
every sample).  This layer is the sparse, TPU-native counterpart: top-1
token routing with a capacity bound, computed as einsum dispatch/combine so
the expert FFNs stay large batched MXU matmuls; homogeneous experts are
vmapped over a stacked parameter tree.  Expert parallelism over a mesh
``expert`` axis lives in ``bigdl_tpu/parallel/expert_parallel.py``.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import init as init_methods
from bigdl_tpu.nn.module import Module


class MixtureOfExperts(Module):
    """Top-1 (Switch) gated mixture of ``n_experts`` homogeneous experts.

    ``expert``: a template Module mapping (tokens, d_model) -> (tokens,
    d_model); its structure is replicated per expert with independent
    parameters (stacked leaf-wise under the ``"experts"`` key).

    Routing: softmax gate over experts, each token goes to its argmax
    expert; each expert processes at most ``capacity`` tokens
    (``ceil(tokens / n_experts * capacity_factor)``), overflow tokens pass
    through with zero expert output (standard Switch behavior).
    """

    def __init__(self, d_model: int, expert: Module, n_experts: int,
                 capacity_factor: float = 1.25, name=None):
        super().__init__(name)
        self.d_model = d_model
        self.expert = expert
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor

    def _init_params(self, rng):
        ks = jax.random.split(rng, self.n_experts + 1)
        xavier = init_methods.Xavier()
        gate = xavier(ks[0], (self.d_model, self.n_experts),
                      self.d_model, self.n_experts)
        per_expert = [self.expert._init_params(k) for k in ks[1:]]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                         *per_expert)
        return {"gate": gate, "experts": stacked}

    def _init_state(self):
        # experts must be stateless: per-expert running statistics are not
        # threaded through the vmapped dispatch (guarded in expert_forward)
        expert_state = self.expert._init_state()
        if jax.tree_util.tree_leaves(expert_state):
            raise ValueError(
                "MixtureOfExperts experts must be stateless (no BatchNorm "
                "running statistics) — state updates cannot be threaded "
                "through the routed dispatch")
        return {"expert": expert_state}

    def capacity(self, n_tokens: int) -> int:
        """Per-expert token capacity for a dispatch over ``n_tokens``.
        Under expert parallelism this applies per device shard (each shard
        routes its local tokens), so the global per-expert budget is
        n_shards * capacity(local_tokens)."""
        return max(1, math.ceil(n_tokens / self.n_experts
                                * self.capacity_factor))

    def route(self, params, flat):
        """(tokens, d) -> (dispatch (t, E, C), combine (t, E, C)).

        ``dispatch`` is the 0/1 routing tensor (token t occupies capacity
        slot c of expert e); ``combine`` additionally carries the gate
        probability, so ``combine @ expert_out`` is the weighted output.
        """
        t = flat.shape[0]
        cap = self.capacity(t)
        gates = jax.nn.softmax(flat @ params["gate"], axis=-1)   # (t, E)
        expert_idx = jnp.argmax(gates, axis=-1)                  # (t,)
        # queue bookkeeping in int32: a low-precision activation dtype
        # (bf16 is first-class here) cannot count past 256 exactly, which
        # would double-book capacity slots
        onehot_i = jax.nn.one_hot(expert_idx, self.n_experts,
                                  dtype=jnp.int32)               # (t, E)
        pos = jnp.cumsum(onehot_i, axis=0) * onehot_i - 1        # (t, E)
        keep = (pos >= 0) & (pos < cap)
        slot = jax.nn.one_hot(jnp.where(keep, pos, -1), cap,
                              dtype=flat.dtype)                  # (t, E, C)
        onehot = onehot_i.astype(flat.dtype)
        dispatch = slot * onehot[:, :, None]
        gate_val = jnp.sum(gates * onehot, axis=-1)              # (t,)
        combine = dispatch * gate_val[:, None, None]
        return dispatch, combine

    def expert_forward(self, params, expert_in, state, training, rng):
        """vmapped expert application over the stacked (E, C, d) inputs."""
        def one(p, xin):
            out, _ = self.expert.apply(p, xin, state["expert"],
                                       training=training, rng=rng)
            return out
        return jax.vmap(one)(params["experts"], expert_in)

    def apply(self, params, input, state, training=False, rng=None):
        flat = jnp.reshape(input, (-1, self.d_model))
        dispatch, combine = self.route(params, flat)
        expert_in = jnp.einsum("tec,td->ecd", dispatch, flat)
        expert_out = self.expert_forward(params, expert_in, state,
                                         training, rng)
        out = jnp.einsum("tec,ecd->td", combine, expert_out)
        return jnp.reshape(out, input.shape), state
