"""Mixture-of-experts layer (Switch-style top-1 routing).

The reference has no MoE (its ``MixtureTable`` is a dense gated blend over
a Table of expert outputs, ``nn/MixtureTable.scala`` — every expert runs on
every sample).  This layer is the sparse, TPU-native counterpart: top-1
token routing with a capacity bound, computed as einsum dispatch/combine so
the expert FFNs stay large batched MXU matmuls; homogeneous experts are
vmapped over a stacked parameter tree.  Expert parallelism over a mesh
``expert`` axis lives in ``bigdl_tpu/parallel/expert_parallel.py``.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import init as init_methods
from bigdl_tpu.nn.module import Module


class MixtureOfExperts(Module):
    """Top-k gated mixture of ``n_experts`` homogeneous experts
    (``top_k=1``: Switch; ``top_k=2``: the GShard configuration).

    ``expert``: a template Module mapping (tokens, d_model) -> (tokens,
    d_model); its structure is replicated per expert with independent
    parameters (stacked leaf-wise under the ``"experts"`` key).

    Routing: softmax gate over experts, each token goes to its ``top_k``
    highest-gate experts with the selected gate values renormalized to
    sum to 1 per token; each expert processes at most ``capacity`` tokens
    per choice tier combined, with overflow contributions dropped to zero
    (standard Switch/GShard behavior).

    **Batch-split semantics.** Capacity-overflow dropping is a property of
    which tokens *compete* for the same expert slots, so any execution
    that splits a batch into independent forwards — GPipe microbatching
    (``parallel.pipeline``), expert-parallel token shards
    (``parallel.expert_parallel``), gradient accumulation — routes each
    split with its *own* capacity budget.  When capacity binds, the result
    therefore differs from a monolithic full-batch forward (different
    tokens drop); the two agree exactly whenever no token drops.  Pass
    ``capacity=`` to pin the per-expert, per-forward budget explicitly
    (e.g. capacity sized to the microbatch), or raise ``capacity_factor``
    to ``n_experts / top_k`` to make dropping impossible and the layer
    batch-split-invariant.  The Switch load-balancing
    diagnostic ``n_experts * sum_e(token_fraction_e * mean_gate_e)``
    (minimized at 1.0 by a uniform router) is returned in the module
    state under ``"aux_loss"``: read it from ``model.state`` after a
    TRAINING-mode forward (the stateful shell persists new state only in
    train mode) or take it from ``apply``'s returned state directly; under
    expert parallelism pass ``return_aux=True`` to
    ``expert_parallel_apply``.
    """

    def __init__(self, d_model: int, expert: Module, n_experts: int,
                 capacity_factor: float = 1.25, top_k: int = 1,
                 capacity: Optional[int] = None, name=None):
        super().__init__(name)
        if not 1 <= top_k <= n_experts:
            raise ValueError(f"top_k {top_k} must be in [1, {n_experts}]")
        if capacity is not None and capacity < 1:
            raise ValueError(f"capacity {capacity} must be >= 1")
        self.d_model = d_model
        self.expert = expert
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.top_k = top_k
        self.fixed_capacity = capacity
        self.expert_parallel = None     # axis name once wired
        self._ep_shards = 1

    def _init_params(self, rng):
        ks = jax.random.split(rng, self.n_experts + 1)
        xavier = init_methods.Xavier()
        gate = xavier(ks[0], (self.d_model, self.n_experts),
                      self.d_model, self.n_experts)
        per_expert = [self.expert._init_params(k) for k in ks[1:]]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                         *per_expert)
        return {"gate": gate, "experts": stacked}

    # aux_loss is a per-forward diagnostic, not cross-step state; the
    # expert's state nests under "expert" and is owned by self.expert
    # (see module.semantic_state_leaves)
    diagnostic_state_keys = ("aux_loss",)

    @property
    def state_children(self):
        return {"expert": self.expert}

    def _init_state(self):
        # experts must be stateless: per-expert running statistics are not
        # threaded through the vmapped dispatch (guarded in expert_forward)
        from bigdl_tpu.nn.module import semantic_state_leaves
        expert_state = self.expert._init_state()
        if semantic_state_leaves(self.expert, expert_state):
            raise ValueError(
                "MixtureOfExperts experts must be stateless (no BatchNorm "
                "running statistics) — state updates cannot be threaded "
                "through the routed dispatch")
        return {"expert": expert_state,
                "aux_loss": jnp.zeros(())}

    def capacity(self, n_tokens: int) -> int:
        """Per-expert token capacity for a dispatch over ``n_tokens``:
        scales with ``top_k`` (each token makes k assignments, so a
        balanced router sends k*t/E per expert — GShard's convention).
        A ``capacity=`` constructor override pins this regardless of the
        forward's token count (stable under batch splitting — see the
        class docstring).  Under expert parallelism this applies per
        device shard (each shard routes its local tokens), so the global
        per-expert budget is n_shards * capacity(local_tokens)."""
        if self.fixed_capacity is not None:
            return self.fixed_capacity
        return max(1, math.ceil(n_tokens * self.top_k / self.n_experts
                                * self.capacity_factor))

    def route(self, params, flat):
        """(tokens, d) -> (dispatch (t, E, C), combine (t, E, C), aux).

        ``dispatch`` is the 0/1 routing tensor (token t occupies capacity
        slot c of expert e); ``combine`` additionally carries the
        (renormalized) gate probability, so ``combine @ expert_out`` is
        the weighted output; ``aux`` is the Switch load-balancing scalar.
        """
        t = flat.shape[0]
        cap = self.capacity(t)
        gates = jax.nn.softmax(flat @ params["gate"], axis=-1)   # (t, E)

        # top-k selection in one op; queue bookkeeping in int32 — a
        # low-precision activation dtype (bf16 is first-class here) cannot
        # count past 256 exactly, which would double-book capacity slots.
        # Later tiers queue AFTER all earlier tiers of the same expert
        # (GShard's ordering), via the per-expert count offset.
        top_gates, top_idx = jax.lax.top_k(gates, self.top_k)    # (t, k)
        counts = jnp.zeros((self.n_experts,), jnp.int32)
        chosen_slot, chosen_gate = [], []
        top1_oh = None                      # tier-0 assignment, for aux
        for k in range(self.top_k):
            oh = jax.nn.one_hot(top_idx[:, k], self.n_experts,
                                dtype=jnp.int32)
            pos = (jnp.cumsum(oh, axis=0) * oh - 1) + counts[None, :] * oh
            keep = (pos >= 0) & (pos < cap) & (oh > 0)
            slot = jax.nn.one_hot(jnp.where(keep, pos, -1), cap,
                                  dtype=flat.dtype)              # (t, E, C)
            if top1_oh is None:
                top1_oh = oh
            chosen_slot.append(slot * oh.astype(flat.dtype)[:, :, None])
            chosen_gate.append(top_gates[:, k])                  # (t,)
            counts = counts + jnp.sum(oh, axis=0)

        # top_k=1 (Switch) scales by the raw gate probability; top_k>1
        # renormalizes the selected gates per token (GShard)
        gate_stack = jnp.stack(chosen_gate, axis=0)              # (k, t)
        if self.top_k > 1:
            denom = jnp.maximum(jnp.sum(gate_stack, axis=0), 1e-9)
        else:
            denom = jnp.ones_like(gate_stack[0])
        dispatch = sum(chosen_slot)
        combine = sum(s * (g / denom)[:, None, None]
                      for s, g in zip(chosen_slot, gate_stack))

        # Switch load-balancing diagnostic over the TOP-1 assignment
        frac_tokens = jnp.mean(top1_oh.astype(gates.dtype), axis=0)
        mean_gate = jnp.mean(gates, axis=0)
        aux = self.n_experts * jnp.sum(frac_tokens * mean_gate)
        return dispatch, combine, aux

    # ---- the grouped execution path (bigdl.moe.impl=grouped) -------------
    #
    # Same routing decisions, different materialization: instead of the
    # O(t*E*C*d) dispatch/combine einsums over mostly-zero (t, E, C)
    # one-hot tensors, the kept (token, tier) assignments scatter
    # directly into the (E, C, d) expert batch and gather back out —
    # O(t*k*d) data movement.  The expert matmuls themselves are
    # unchanged (the same grouped (E, C, d) batch), and the kept set,
    # slot order, renormalized gates and aux diagnostic are computed by
    # the identical bookkeeping, so capacity-drop semantics are exact.

    @staticmethod
    def _impl() -> str:
        from bigdl_tpu.utils import config
        impl = str(config.get_property("bigdl.moe.impl", "einsum")
                   or "einsum").lower()
        if impl not in ("einsum", "grouped"):
            raise ValueError(f"bigdl.moe.impl={impl!r}: expected 'einsum' "
                             "or 'grouped'")
        return impl

    def route_compact(self, params, flat):
        """(tokens, d) -> (expert_id (t, k), slot (t, k), weight (t, k),
        keep (t, k), aux) — :meth:`route`'s bookkeeping in token-major
        compact form.  ``slot`` is the capacity position the assignment
        would occupy (tier k queues after all earlier tiers of the same
        expert, via the per-expert count offset — GShard's ordering);
        ``keep`` is False past capacity; ``weight`` is the (renormalized)
        gate with the keep mask already applied, so a dropped assignment
        contributes exactly zero."""
        t = flat.shape[0]
        cap = self.capacity(t)
        gates = jax.nn.softmax(flat @ params["gate"], axis=-1)   # (t, E)
        top_gates, top_idx = jax.lax.top_k(gates, self.top_k)    # (t, k)
        counts = jnp.zeros((self.n_experts,), jnp.int32)
        slots_l, keeps_l = [], []
        top1_oh = None
        for k in range(self.top_k):
            oh = jax.nn.one_hot(top_idx[:, k], self.n_experts,
                                dtype=jnp.int32)
            pos = (jnp.cumsum(oh, axis=0) * oh - 1) + counts[None, :] * oh
            # the chosen column's value IS this assignment's queue
            # position (>= 0 there by construction)
            slot_k = jnp.take_along_axis(pos, top_idx[:, k:k + 1],
                                         axis=1)[:, 0]
            if top1_oh is None:
                top1_oh = oh
            slots_l.append(slot_k)
            keeps_l.append(slot_k < cap)
            counts = counts + jnp.sum(oh, axis=0)
        slot = jnp.stack(slots_l, axis=1)                        # (t, k)
        keep = jnp.stack(keeps_l, axis=1)                        # (t, k)
        if self.top_k > 1:
            denom = jnp.maximum(jnp.sum(top_gates, axis=1, keepdims=True),
                                1e-9)
        else:
            denom = jnp.ones_like(top_gates)
        wgt = (top_gates / denom) * keep.astype(flat.dtype)
        frac_tokens = jnp.mean(top1_oh.astype(gates.dtype), axis=0)
        mean_gate = jnp.mean(gates, axis=0)
        aux = self.n_experts * jnp.sum(frac_tokens * mean_gate)
        return top_idx, slot, wgt, keep, aux

    def grouped_dispatch(self, flat, expert_id, slot, keep, cap: int):
        """Scatter kept assignments into the (E, C, d) expert batch: row
        ``expert_id * C + slot`` receives the token vector; dropped
        assignments target a discarded overflow row.  Kept rows are
        unique by construction (cumsum slot assignment), so the
        scatter-add materializes exactly what the dispatch einsum
        builds, with unfilled capacity slots staying zero."""
        t, d = flat.shape
        dump = self.n_experts * cap                 # overflow row, discarded
        rows = jnp.where(keep, expert_id * cap + slot, dump)     # (t, k)
        tok = jnp.repeat(jnp.arange(t), self.top_k)
        buf = jnp.zeros((dump + 1, d), flat.dtype)
        buf = buf.at[rows.reshape(-1)].add(flat[tok])
        return buf[:dump].reshape(self.n_experts, cap, d)

    def grouped_combine(self, expert_out, expert_id, slot, wgt, keep,
                        cap: int):
        """Gather each assignment's expert-output row and weighted-sum
        over the k tiers — the combine einsum without the (t, E, C)
        intermediate.  ``wgt`` carries the keep mask, so dropped
        assignments add zero (they gather an arbitrary row, then
        multiply by 0)."""
        d = expert_out.shape[-1]
        rows = jnp.where(keep, expert_id * cap + slot, 0)        # (t, k)
        picked = expert_out.reshape(self.n_experts * cap, d)[
            rows.reshape(-1)].reshape(rows.shape + (d,))         # (t, k, d)
        return jnp.sum(picked * wgt[:, :, None], axis=1)

    def set_expert_parallel(self, axis_name, n_shards: int
                            ) -> "MixtureOfExperts":
        """Wire the trainer's mesh ``expert`` axis (duck-typed, like
        MultiHeadAttention's ring path): while that axis is bound —
        inside the distributed trainer's shard_map step — ``apply``
        switches to the all_to_all dispatch, each device running only
        its ``n_experts / n_shards`` experts on the tokens every peer
        routed to them.  Outside the axis (validation, plain forward)
        the dense path runs unchanged."""
        if axis_name is not None and self.n_experts % n_shards != 0:
            raise ValueError(
                f"n_experts {self.n_experts} must divide by the expert "
                f"axis size {n_shards}")
        self.expert_parallel = axis_name
        self._ep_shards = n_shards if axis_name is not None else 1
        self._jit_apply = None
        return self

    def expert_forward(self, params, expert_in, state, training, rng,
                       experts=None):
        """vmapped expert application over the stacked (E, C, d) inputs.
        ``experts`` overrides the stacked tree (the expert-parallel path
        passes this device's slice)."""
        stacked = params["experts"] if experts is None else experts

        def one(p, xin):
            out, _ = self.expert.apply(p, xin, state["expert"],
                                       training=training, rng=rng)
            return out
        return jax.vmap(one)(stacked, expert_in)

    def apply(self, params, input, state, training=False, rng=None):
        from bigdl_tpu.nn.attention import _axis_bound
        flat = jnp.reshape(input, (-1, self.d_model))
        ep = self.expert_parallel
        if ep is not None and _axis_bound(ep):
            out, aux = self._apply_expert_parallel(params, flat, state,
                                                   training, rng)
        elif self._impl() == "grouped":
            eid, slot, wgt, keep, aux = self.route_compact(params, flat)
            cap = self.capacity(flat.shape[0])
            expert_in = self.grouped_dispatch(flat, eid, slot, keep, cap)
            expert_out = self.expert_forward(params, expert_in, state,
                                             training, rng)
            out = self.grouped_combine(expert_out, eid, slot, wgt, keep,
                                       cap)
        else:
            dispatch, combine, aux = self.route(params, flat)
            expert_in = jnp.einsum("tec,td->ecd", dispatch, flat)
            expert_out = self.expert_forward(params, expert_in, state,
                                             training, rng)
            out = jnp.einsum("tec,ecd->td", combine, expert_out)
        new_state = dict(state)
        new_state["aux_loss"] = aux
        return jnp.reshape(out, input.shape), new_state

    def _apply_expert_parallel(self, params, flat, state, training, rng):
        """In-axis all_to_all dispatch (tokens already sharded over the
        bound ``expert`` axis; params replicated — the trainer's ARP
        keeps one flat replicated vector).  Same exchange geometry as
        ``parallel/expert_parallel.expert_parallel_apply``: route local
        tokens against the full gate, all_to_all the per-expert queues,
        run only THIS device's expert block, all_to_all back, combine.
        The aux diagnostic is pmeant over the token shards here so the
        trainer's loss term sees the global balance."""
        from jax import lax
        ep, n = self.expert_parallel, self._ep_shards
        grouped = self._impl() == "grouped"
        if grouped:
            # grouped path: only the LOCAL dispatch/combine
            # materialization changes — the all_to_all exchange geometry
            # and per-shard capacity semantics are identical
            eid, slot, wgt, keep, aux = self.route_compact(params, flat)
            cap = self.capacity(flat.shape[0])
            expert_in = self.grouped_dispatch(flat, eid, slot, keep, cap)
        else:
            dispatch, combine, aux = self.route(params, flat)
            expert_in = jnp.einsum("tec,td->ecd", dispatch, flat)
        # (E, C, d) -> (E/n, n*C, d): every peer's tokens for my experts
        expert_in = lax.all_to_all(expert_in, ep, split_axis=0,
                                   concat_axis=1, tiled=True)
        e_per = self.n_experts // n
        start = lax.axis_index(ep) * e_per
        mine = jax.tree_util.tree_map(
            lambda a: lax.dynamic_slice_in_dim(a, start, e_per, 0),
            params["experts"])
        out = self.expert_forward(params, expert_in, state, training, rng,
                                  experts=mine)
        out = lax.all_to_all(out, ep, split_axis=1, concat_axis=0,
                             tiled=True)                     # (E, C, d)
        if grouped:
            y = self.grouped_combine(out, eid, slot, wgt, keep, cap)
        else:
            y = jnp.einsum("tec,ecd->td", combine, out)
        return y, lax.pmean(aux, ep)
