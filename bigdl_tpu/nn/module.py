"""Module system: functional core + stateful Torch-style shell.

Reference equivalent: ``nn/abstractnn/AbstractModule.scala:54`` — Torch-style
modules with mutable ``output``/``gradInput`` caches and explicit
``updateOutput`` / ``updateGradInput`` / ``accGradParameters``.

The TPU-native design inverts this.  Every module defines ONE pure function::

    apply(params, input, state, training=False, rng=None) -> (output, new_state)

- ``params``  : pytree of trainable arrays (a dict for leaves, a list of child
                pytrees for containers);
- ``state``   : pytree of non-trainable buffers (e.g. BatchNormalization
                running statistics); ``{}`` for the common stateless case;
- ``input``   : an *Activity* — a jax array or an arbitrarily nested
                list/tuple/dict of arrays (the reference's ``Table``,
                ``nn/abstractnn/Activity.scala:32``);
- ``rng``     : jax PRNG key for stochastic layers (Dropout, RReLU).

Whole models compose into one pure function, so training steps fuse under a
single ``jax.jit`` + ``jax.value_and_grad`` — XLA sees the entire graph and
schedules it onto the MXU, instead of the reference's layer-at-a-time MKL
dispatch.  The familiar imperative surface (``forward``, ``backward``,
``zero_grad_parameters``, ``get_parameters``) is preserved as a thin shell over
the pure core: ``backward`` is ``jax.vjp`` of ``apply``, gradient accumulation
(the reference's ``accGradParameters``) is a pytree add in the shell.
"""

from __future__ import annotations

import itertools
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.engine import Engine

# An Activity is a jax array or a nested list/tuple/dict of them.
Activity = Any
Params = Any
State = Any


def tree_zeros_like(tree):
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def tree_add(a, b):
    if a is None:
        return b
    return jax.tree_util.tree_map(jnp.add, a, b)


def semantic_state_leaves(module, state=None):
    """State leaves of ``module`` excluding per-forward diagnostics: the
    leaves whose values must actually thread across steps.

    A module opts its OWN top-level state keys out by declaring them in
    ``diagnostic_state_keys`` (e.g. MixtureOfExperts' load-balance scalar
    ``aux_loss``) — the exclusion is scoped to that module, not a global
    key-name blocklist, so an unrelated module storing genuine cross-step
    state under the same name still trips "stateless" guards.  Modules
    that nest another module's state under a key (MoE's ``"expert"``)
    declare the mapping in ``state_children`` so the walk recurses with
    the right owner.  ``state`` overrides the module's live state (used
    to check a freshly built sub-state before it is installed)."""
    if state is None:
        module._ensure_init()
        state = module.state
    if isinstance(module, Container):
        return [leaf for child, s in zip(module.children, state)
                for leaf in semantic_state_leaves(child, s)]
    if isinstance(state, dict):
        sub = getattr(module, "state_children", {}) or {}
        diag = getattr(module, "diagnostic_state_keys", ()) or ()
        out = []
        for k, v in state.items():
            if k in diag:
                continue
            if k in sub:
                out.extend(semantic_state_leaves(sub[k], v))
            else:
                out.extend(jax.tree_util.tree_leaves(v))
        return out
    return jax.tree_util.tree_leaves(state)


def collect_diagnostics(module, state, key: str):
    """Collect every DECLARED per-forward diagnostic named ``key`` from a
    state tree, walking modules in parallel (same ownership rules as
    :func:`semantic_state_leaves`).  Trainers use this to fold MoE's
    ``aux_loss`` load-balancing term into the objective — only modules
    that declared the key contribute, so an unrelated state entry with
    the same name is never swept into the loss."""
    out = []
    if isinstance(module, Container):
        for child, s in zip(module.children, state):
            out.extend(collect_diagnostics(child, s, key))
        return out
    if isinstance(state, dict):
        diag = getattr(module, "diagnostic_state_keys", ()) or ()
        sub = getattr(module, "state_children", {}) or {}
        if key in diag and key in state:
            out.append(state[key])
        for k, v in state.items():
            if k in sub:
                out.extend(collect_diagnostics(sub[k], v, key))
    return out


def _child_rng(rng, i: int):
    return None if rng is None else jax.random.fold_in(rng, i)


class Module:
    """Base class of all layers and containers.

    Subclasses must implement :meth:`_init_params` (and optionally
    :meth:`_init_state`) plus the pure :meth:`apply`.
    """

    _name_seq = itertools.count()

    # ---- data-layout contract (channels-last compute path) --------------
    # How this module relates to the data format of image activations
    # (see nn/layout.py, which uses this to move a convnet's interior to
    # the TPU-native NHWC layout while the public API stays NCHW):
    #   "opaque"   — layout-dependent or unknown: must see the Torch-facade
    #                NCHW activations (the safe default);
    #   "agnostic" — elementwise/broadcast: whatever layout flows in flows
    #                out unchanged;
    #   "spatial"  — consumes image maps in ``self.format`` and can be
    #                re-pointed between "NCHW" and "NHWC" via
    #                :meth:`set_format`.
    layout_role = "opaque"

    # ---- declarable IO contract (bigdl_tpu.analysis.contracts) ----------
    # A ModuleContract (input rank(s), dtype policy, promotion expectation)
    # that the static contract checker verifies with jax.eval_shape — no
    # FLOPs.  Class attribute for layer families (conv/pool/BN declare
    # theirs), instance attribute via declare_contract for one-offs.
    contract = None

    def declare_contract(self, **kwargs) -> "Module":
        """Attach a per-instance IO contract, e.g.
        ``m.declare_contract(input_ndim=(2, 3), dtypes="float")`` —
        checked by :func:`bigdl_tpu.analysis.check_model`."""
        from bigdl_tpu.analysis.contracts import ModuleContract
        self.contract = ModuleContract(**kwargs)
        return self

    def __init__(self, name: Optional[str] = None):
        self.name = name or f"{type(self).__name__}_{next(Module._name_seq)}"
        self.train_mode: bool = True
        # Imperative-shell caches (reference AbstractModule.output/gradInput)
        self.output: Activity = None
        self.grad_input: Activity = None
        # Gradient scaling (reference scaleW/scaleB via setScaleW/setScaleB)
        self.scale_w: float = 1.0
        self.scale_b: float = 1.0
        # Per-layer regularizers, consumed by the training-loss builder
        self.w_regularizer = None
        self.b_regularizer = None
        self._params: Optional[Params] = None
        self._state: Optional[State] = None
        self._grads: Optional[Params] = None
        self._last_rng = None
        self._fwd_state_in = None
        self._rng_seq = itertools.count(1)
        self._jit_apply = None
        # forward/backward nanosecond timing (reference AbstractModule:193-204)
        self.forward_time: int = 0
        self.backward_time: int = 0

    # ---- pure core ------------------------------------------------------

    def _init_params(self, rng) -> Params:
        """Create the trainable parameter pytree.  ``{}`` if none."""
        return {}

    def _init_state(self) -> State:
        """Create the non-trainable state pytree.  ``{}`` if none."""
        return {}

    def apply(self, params: Params, input: Activity, state: State,
              training: bool = False, rng=None) -> Tuple[Activity, State]:
        """The pure forward function.  MUST be overridden, MUST NOT mutate."""
        raise NotImplementedError(type(self).__name__)

    # ---- parameter lifecycle -------------------------------------------

    def reset(self, rng=None) -> "Module":
        """(Re-)initialise parameters (reference ``reset()``)."""
        if rng is None:
            rng = jax.random.PRNGKey(Engine.get_seed() + hash(self.name) % (2 ** 31))
        self._params = self._init_params(rng)
        self._state = self._init_state()
        self._grads = tree_zeros_like(self._params)
        self._jit_apply = None
        return self

    def _ensure_init(self):
        if self._params is None:
            self.reset()

    @property
    def params(self) -> Params:
        self._ensure_init()
        return self._params

    @params.setter
    def params(self, value: Params):
        self._ensure_init()
        self._params = value

    @property
    def state(self) -> State:
        self._ensure_init()
        return self._state

    @state.setter
    def state(self, value: State):
        self._state = value

    @property
    def grads(self) -> Params:
        self._ensure_init()
        return self._grads

    # ---- imperative shell ----------------------------------------------

    def forward(self, input: Activity, rng=None) -> Activity:
        """Stateful forward (reference ``AbstractModule.forward:213``).
        Wall time accumulates into ``forward_time`` (the reference's
        per-module nanosecond timing, ``AbstractModule:193-204``); the
        device is synced for an honest measurement — this shell is the
        debugging/parity path, not the fused training hot loop."""
        self._ensure_init()
        if rng is None and self.is_stochastic() and self.train_mode:
            rng = jax.random.PRNGKey(
                np.random.SeedSequence([Engine.get_seed(), next(self._rng_seq)])
                .generate_state(1)[0])
        self._last_rng = rng
        self._fwd_state_in = self._state
        t0 = time.time_ns()
        out, new_state = self._jitted()(self._params, input, self._state, rng)
        jax.block_until_ready(out)
        self.forward_time += time.time_ns() - t0
        if self.train_mode:
            self._state = new_state
        self.output = out
        return out

    def update_output(self, input: Activity) -> Activity:
        return self.forward(input)

    def backward(self, input: Activity, grad_output: Activity) -> Activity:
        """updateGradInput + accGradParameters in one VJP
        (reference ``AbstractModule.backward:231``)."""
        self._ensure_init()
        state_in = self._fwd_state_in if self._fwd_state_in is not None else self._state
        rng = self._last_rng

        def f(p, x):
            out, _ = self.apply(p, x, state_in, training=self.train_mode, rng=rng)
            return out

        t0 = time.time_ns()
        _, vjp = jax.vjp(f, self._params, input)
        pgrads, gin = vjp(grad_output)
        jax.block_until_ready(gin)
        self.backward_time += time.time_ns() - t0
        pgrads = self._scale_grads(pgrads)
        self._grads = tree_add(self._grads, pgrads)
        self.grad_input = gin
        return gin

    def update_grad_input(self, input: Activity, grad_output: Activity) -> Activity:
        """Input gradient only, no parameter-gradient accumulation."""
        self._ensure_init()
        state_in = self._fwd_state_in if self._fwd_state_in is not None else self._state
        rng = self._last_rng

        def f(x):
            out, _ = self.apply(self._params, x, state_in,
                                training=self.train_mode, rng=rng)
            return out

        _, vjp = jax.vjp(f, input)
        (gin,) = vjp(grad_output)
        self.grad_input = gin
        return gin

    def acc_grad_parameters(self, input: Activity, grad_output: Activity) -> None:
        self._ensure_init()
        state_in = self._fwd_state_in if self._fwd_state_in is not None else self._state
        rng = self._last_rng

        def f(p):
            out, _ = self.apply(p, input, state_in,
                                training=self.train_mode, rng=rng)
            return out

        _, vjp = jax.vjp(f, self._params)
        (pgrads,) = vjp(grad_output)
        self._grads = tree_add(self._grads, self._scale_grads(pgrads))

    def _scale_grads(self, pgrads):
        if self.scale_w == 1.0 and self.scale_b == 1.0:
            return pgrads
        def scale(path, g):
            leaf = path[-1].key if hasattr(path[-1], "key") else None
            s = self.scale_b if leaf == "bias" else self.scale_w
            return g * s
        return jax.tree_util.tree_map_with_path(scale, pgrads)

    def _jitted(self):
        if self._jit_apply is None:
            def fn(params, input, state, rng, training):
                return self.apply(params, input, state, training=training, rng=rng)
            # the imperative debugging/parity shell, not a fused step:
            # every hot-path compile routes through
            # utils.compile_cache.tracked_jit
            jitted = jax.jit(fn, static_argnums=(4,))  # lint: allow(untracked-jit)
            self._jit_apply = lambda p, x, s, r: jitted(p, x, s, r, self.train_mode)
        return self._jit_apply

    # ---- mode / traversal ----------------------------------------------

    def set_format(self, format: str) -> "Module":
        """Switch a spatial module's compute data format ("NCHW"/"NHWC").

        Clears this module's own jit cache; an ENCLOSING container that
        already traced this module keeps its old-format trace — call
        :meth:`clear_jit_cache` on the outermost model after re-pointing
        modules inside a live one (``nn.to_channels_last`` does)."""
        if self.layout_role != "spatial":
            raise ValueError(
                f"{type(self).__name__} has no data format (layout_role="
                f"{self.layout_role!r})")
        if format not in ("NCHW", "NHWC"):
            raise ValueError(f"unknown data format {format!r}")
        self.format = format
        self.clear_jit_cache(recursive=False)
        return self

    def clear_jit_cache(self, recursive: bool = True) -> "Module":
        """Drop cached jitted traces (forward shell + eval forward) so the
        next call re-traces — required after structural or format edits on
        an already-run model.  ``recursive`` walks the whole subtree."""
        for m in (self.modules() if recursive else (self,)):
            m._jit_apply = None
            m.__dict__.pop("_eval_jit", None)
        return self

    def is_stochastic(self) -> bool:
        """True if apply consumes rng during training (Dropout etc.)."""
        return False

    def training(self) -> "Module":
        self.train_mode = True
        self._jit_apply = None
        return self

    def evaluate(self, *args, **kwargs):
        """No args: switch to eval mode (reference ``evaluate()``).
        With (dataset, methods): run distributed evaluation."""
        if not args:
            self.train_mode = False
            self._jit_apply = None
            return self
        from bigdl_tpu.optim.evaluator import Evaluator
        return Evaluator(self).test(*args, **kwargs)

    def modules(self) -> List["Module"]:
        """All modules in the tree, depth-first, self included."""
        return [self]

    def find_modules(self, cls) -> List["Module"]:
        return [m for m in self.modules() if isinstance(m, cls)]

    def get_times(self) -> List[Tuple["Module", int, int]]:
        return [(m, m.forward_time, m.backward_time) for m in self.modules()]

    def reset_times(self) -> None:
        for m in self.modules():
            m.forward_time = 0
            m.backward_time = 0

    # ---- parameters API -------------------------------------------------

    def parameters(self) -> Tuple[Params, Params]:
        """(params pytree, grads pytree) — reference ``parameters()``."""
        self._ensure_init()
        return self._params, self._grads

    def get_parameters(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Flattened (weights, gradients) vectors
        (reference ``getParameters()`` / ``Module.flatten``, ``nn/Module.scala:80``).
        Returns concatenated copies; use :meth:`set_flat_parameters` to write back.

        The flatten runs on the HOST: after distributed training the leaf
        arrays carry heterogeneous shardings (replicated LayerNorm next to
        a Megatron-split weight), and jax 0.4.x's eager
        ``jnp.concatenate`` over mixed-sharding operands on a multi-axis
        mesh miscomputes — every element comes back scaled by the product
        of the mesh axes absent from the spec (observed 16x on a
        ('data','stage','model') mesh).  ``device_get`` + numpy sidesteps
        the partitioner entirely; the copies this API documents were
        always host-bound anyway.
        """
        self._ensure_init()
        leaves = jax.tree_util.tree_leaves(self._params)
        gleaves = jax.tree_util.tree_leaves(self._grads)
        if not leaves:
            return jnp.zeros((0,)), jnp.zeros((0,))
        w = np.concatenate([np.ravel(l) for l in jax.device_get(leaves)])
        g = np.concatenate([np.ravel(l) for l in jax.device_get(gleaves)])
        return jnp.asarray(w), jnp.asarray(g)

    def set_flat_parameters(self, flat: jnp.ndarray) -> None:
        self._ensure_init()
        leaves, treedef = jax.tree_util.tree_flatten(self._params)
        out, off = [], 0
        for l in leaves:
            n = int(np.prod(l.shape)) if l.shape else 1
            out.append(jnp.reshape(flat[off:off + n], l.shape).astype(l.dtype))
            off += n
        self._params = jax.tree_util.tree_unflatten(treedef, out)

    def n_parameters(self) -> int:
        self._ensure_init()
        return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(self._params))

    def zero_grad_parameters(self) -> None:
        self._ensure_init()
        self._grads = tree_zeros_like(self._params)

    def update_parameters(self, learning_rate: float) -> None:
        """Vanilla in-place SGD step (reference ``updateParameters``)."""
        self._ensure_init()
        self._params = jax.tree_util.tree_map(
            lambda p, g: p - learning_rate * g, self._params, self._grads)

    def get_parameters_table(self) -> Dict[str, Params]:
        """{layer name: params} (reference ``getParametersTable()``)."""
        out = {}
        for m in self.modules():
            if not isinstance(m, Container) and m.params:
                out[m.name] = m.params
        return out

    # ---- graph-node builder --------------------------------------------

    def inputs(self, *nodes):
        """Build a graph node: ``layer.inputs(node1, node2)``
        (reference ``AbstractModule.inputs:539``)."""
        from bigdl_tpu.nn.graph import ModuleNode
        return ModuleNode(self).inputs(*nodes)

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    # ---- clone / persistence -------------------------------------------

    def clone_module(self) -> "Module":
        """Deep copy (reference ``cloneModule:353``)."""
        return pickle.loads(pickle.dumps(self))

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_jit_apply"] = None
        d.pop("_eval_jit", None)
        d["_last_rng"] = None
        d["_fwd_state_in"] = None
        d["_rng_seq"] = None
        for key in ("_params", "_state", "_grads"):
            if d.get(key) is not None:
                d[key] = jax.tree_util.tree_map(np.asarray, d[key])
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._rng_seq = itertools.count(1)
        for key in ("_params", "_state", "_grads"):
            if getattr(self, key, None) is not None:
                setattr(self, key,
                        jax.tree_util.tree_map(jnp.asarray, getattr(self, key)))

    def save(self, path: str, overwrite: bool = True) -> "Module":
        from bigdl_tpu.utils import file_io
        file_io.save(self, path, overwrite)
        return self

    # ---- prediction conveniences ---------------------------------------

    def predict(self, dataset, batch_size: int = 32, fold_bn: bool = False):
        from bigdl_tpu.optim.predictor import Predictor
        return Predictor(self, fold_bn=fold_bn).predict(dataset, batch_size)

    def predict_class(self, dataset, batch_size: int = 32,
                      fold_bn: bool = False):
        from bigdl_tpu.optim.predictor import Predictor
        return Predictor(self, fold_bn=fold_bn).predict_class(dataset,
                                                              batch_size)

    def __repr__(self):
        return f"{type(self).__name__}({self.name})"


class Criterion:
    """Loss base class (reference ``nn/abstractnn/AbstractCriterion.scala:49``).

    Pure core: ``apply(input, target) -> scalar loss``.  Shell mirrors the
    reference: ``forward`` caches ``output``, ``backward`` is the VJP.
    """

    def __init__(self):
        self.output = None
        self.grad_input = None
        self.size_average = True

    def apply(self, input: Activity, target: Activity) -> jnp.ndarray:
        raise NotImplementedError(type(self).__name__)

    def forward(self, input: Activity, target: Activity):
        self.output = self.apply(input, target)
        return self.output

    def backward(self, input: Activity, target: Activity):
        _, vjp = jax.vjp(lambda x: self.apply(x, target), input)
        (self.grad_input,) = vjp(jnp.ones(()))
        return self.grad_input

    def update_grad_input(self, input, target):
        return self.backward(input, target)

    def __call__(self, input, target):
        return self.forward(input, target)


class Container(Module):
    """Module with children (reference ``nn/Container.scala:40``).

    Child params are a list aligned with ``self.children``; same for state.
    """

    def __init__(self, name: Optional[str] = None):
        super().__init__(name)
        self.children: List[Module] = []

    def add(self, module: Module) -> "Container":
        self.children.append(module)
        if self._params is not None:
            # adding to an ALREADY-INITIALIZED container (Torch allows
            # add() at any time): bring the new child's params in now — a
            # params list shorter than children would IndexError at the
            # next apply
            module._ensure_init()
            self._params.append(module._params)
            self._state.append(module._state)
            if self._grads is not None:
                self._grads.append(module._grads)
        self._jit_apply = None
        self.__dict__.pop("_eval_jit", None)
        return self

    def _init_params(self, rng) -> Params:
        return [c._init_params(_child_rng(rng, i))
                for i, c in enumerate(self.children)]

    def _init_state(self) -> State:
        return [c._init_state() for c in self.children]

    def reset(self, rng=None) -> "Module":
        super().reset(rng)
        self._adopt()
        return self

    def _adopt(self):
        """Give each child a view of its slice of params/state so individual
        child.forward() keeps working (shared, not copied — functionally
        rebuilt on sync)."""
        for i, c in enumerate(self.children):
            c._params = self._params[i]
            c._state = self._state[i]
            c._grads = self._grads[i]
            if isinstance(c, Container):
                c._adopt()

    def _ensure_init(self):
        if self._params is None:
            # adopt any pre-initialised children rather than clobbering them
            if any(c._params is not None for c in self.children):
                for c in self.children:
                    c._ensure_init()
                self._params = [c._params for c in self.children]
                self._state = [c._state for c in self.children]
                self._grads = [c._grads for c in self.children]
            else:
                self.reset()

    def training(self) -> "Module":
        super().training()
        for c in self.children:
            c.training()
        return self

    def evaluate(self, *args, **kwargs):
        if not args:
            super().evaluate()
            for c in self.children:
                c.evaluate()
            return self
        return super().evaluate(*args, **kwargs)

    def is_stochastic(self) -> bool:
        return any(c.is_stochastic() for c in self.children)

    def modules(self) -> List[Module]:
        out: List[Module] = [self]
        for c in self.children:
            out.extend(c.modules())
        return out

    def zero_grad_parameters(self) -> None:
        super().zero_grad_parameters()
        self._adopt()

    def __getitem__(self, i: int) -> Module:
        return self.children[i]

    def __len__(self) -> int:
        return len(self.children)

    def __repr__(self):
        inner = ", ".join(repr(c) for c in self.children)
        return f"{type(self).__name__}[{inner}]"


class Sequential(Container):
    """Ordered pipeline (reference ``nn/Sequential.scala:30``)."""

    def apply(self, params, input, state, training=False, rng=None):
        x = input
        new_states = []
        for i, child in enumerate(self.children):
            x, s = child.apply(params[i], x, state[i],
                               training=training, rng=_child_rng(rng, i))
            new_states.append(s)
        return x, new_states
