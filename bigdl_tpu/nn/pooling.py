"""Pooling layers.

Reference: ``nn/SpatialMaxPooling.scala``, ``nn/SpatialAveragePooling.scala``,
``nn/VolumetricMaxPooling.scala``, ``nn/RoiPooling.scala``.
"""

from __future__ import annotations

import jax.numpy as jnp

from bigdl_tpu.analysis.contracts import ModuleContract
from bigdl_tpu.nn.module import Module
from bigdl_tpu import ops


class SpatialMaxPooling(Module):
    """2-D max pooling (reference ``nn/SpatialMaxPooling.scala``)."""

    layout_role = "spatial"
    contract = ModuleContract(input_ndim=(3, 4), dtypes="float")

    def __init__(self, kw: int, kh: int, dw: int = None, dh: int = None,
                 pad_w: int = 0, pad_h: int = 0, format: str = "NCHW",
                 name=None):
        super().__init__(name)
        self.kw, self.kh = kw, kh
        self.dw = dw if dw is not None else kw
        self.dh = dh if dh is not None else kh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.ceil_mode = False
        self.format = format

    def ceil(self):
        self.ceil_mode = True
        self._jit_apply = None
        return self

    def floor(self):
        self.ceil_mode = False
        self._jit_apply = None
        return self

    def apply(self, params, input, state, training=False, rng=None):
        squeeze = input.ndim == 3
        if squeeze:
            input = input[None]
        out = ops.max_pool2d(input, (self.kh, self.kw), (self.dh, self.dw),
                             (self.pad_h, self.pad_w), self.ceil_mode,
                             self.format)
        if squeeze:
            out = out[0]
        return out, state


class SpatialAveragePooling(Module):
    """2-D average pooling (reference ``nn/SpatialAveragePooling.scala``)."""

    layout_role = "spatial"
    contract = ModuleContract(input_ndim=(3, 4), dtypes="float")

    def __init__(self, kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 global_pooling: bool = False,
                 ceil_mode: bool = False, count_include_pad: bool = True,
                 divide: bool = True, format: str = "NCHW", name=None):
        super().__init__(name)
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.global_pooling = global_pooling
        self.ceil_mode = ceil_mode
        self.count_include_pad = count_include_pad
        self.divide = divide
        self.format = format

    def ceil(self):
        self.ceil_mode = True
        self._jit_apply = None
        return self

    def apply(self, params, input, state, training=False, rng=None):
        squeeze = input.ndim == 3
        if squeeze:
            input = input[None]
        h_ax, w_ax = (2, 3) if self.format == "NCHW" else (1, 2)
        kh, kw = (input.shape[h_ax], input.shape[w_ax]) \
            if self.global_pooling else (self.kh, self.kw)
        out = ops.avg_pool2d(input, (kh, kw), (self.dh, self.dw),
                             (self.pad_h, self.pad_w), self.ceil_mode,
                             self.count_include_pad, self.format)
        if not self.divide:
            out = out * (kh * kw)
        if squeeze:
            out = out[0]
        return out, state


class VolumetricMaxPooling(Module):
    """3-D max pooling over (N, C, D, H, W)
    (reference ``nn/VolumetricMaxPooling.scala``)."""

    def __init__(self, k_t: int, k_w: int, k_h: int,
                 d_t: int = None, d_w: int = None, d_h: int = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0, name=None):
        super().__init__(name)
        self.k_t, self.k_w, self.k_h = k_t, k_w, k_h
        self.d_t = d_t if d_t is not None else k_t
        self.d_w = d_w if d_w is not None else k_w
        self.d_h = d_h if d_h is not None else k_h
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.ceil_mode = False

    def ceil(self):
        self.ceil_mode = True
        self._jit_apply = None
        return self

    def apply(self, params, input, state, training=False, rng=None):
        squeeze = input.ndim == 4
        if squeeze:
            input = input[None]
        out = ops.max_pool3d(input, (self.k_t, self.k_h, self.k_w),
                             (self.d_t, self.d_h, self.d_w),
                             (self.pad_t, self.pad_h, self.pad_w),
                             self.ceil_mode)
        if squeeze:
            out = out[0]
        return out, state


class RoiPooling(Module):
    """Region-of-interest max pooling (reference ``nn/RoiPooling.scala``).

    Input: Table [data (N,C,H,W), rois (R,5) — (batch_idx, x1, y1, x2, y2)].
    Output: (R, C, pooled_h, pooled_w).
    """

    def __init__(self, pooled_w: int, pooled_h: int, spatial_scale: float,
                 name=None):
        super().__init__(name)
        self.pooled_w = pooled_w
        self.pooled_h = pooled_h
        self.spatial_scale = spatial_scale

    def apply(self, params, input, state, training=False, rng=None):
        import jax
        data, rois = input[0], input[1]
        n, c, h, w = data.shape

        def pool_one(roi):
            batch_idx = roi[0].astype(jnp.int32)
            x1 = jnp.round(roi[1] * self.spatial_scale).astype(jnp.int32)
            y1 = jnp.round(roi[2] * self.spatial_scale).astype(jnp.int32)
            x2 = jnp.round(roi[3] * self.spatial_scale).astype(jnp.int32)
            y2 = jnp.round(roi[4] * self.spatial_scale).astype(jnp.int32)
            roi_h = jnp.maximum(y2 - y1 + 1, 1)
            roi_w = jnp.maximum(x2 - x1 + 1, 1)
            bin_h = roi_h.astype(jnp.float32) / self.pooled_h
            bin_w = roi_w.astype(jnp.float32) / self.pooled_w
            img = data[batch_idx]  # (C, H, W)

            ys = jnp.arange(h)[None, :]      # (1, H)
            xs = jnp.arange(w)[None, :]      # (1, W)
            ph = jnp.arange(self.pooled_h)[:, None]
            pw = jnp.arange(self.pooled_w)[:, None]
            hstart = y1 + jnp.floor(ph * bin_h).astype(jnp.int32)
            hend = y1 + jnp.ceil((ph + 1) * bin_h).astype(jnp.int32)
            wstart = x1 + jnp.floor(pw * bin_w).astype(jnp.int32)
            wend = x1 + jnp.ceil((pw + 1) * bin_w).astype(jnp.int32)
            hmask = (ys >= jnp.clip(hstart, 0, h)) & (ys < jnp.clip(hend, 0, h))
            wmask = (xs >= jnp.clip(wstart, 0, w)) & (xs < jnp.clip(wend, 0, w))
            # (ph, pw, H, W) bin membership mask
            mask = hmask[:, None, :, None] & wmask[None, :, None, :]
            neg = jnp.asarray(-jnp.inf, data.dtype)
            vals = jnp.where(mask[None], img[:, None, None, :, :], neg)
            pooled = jnp.max(vals, axis=(3, 4))
            # empty bins produce 0 (torch semantics)
            any_mask = jnp.any(mask, axis=(2, 3))
            return jnp.where(any_mask[None], pooled, 0.0)

        out = jax.vmap(pool_one)(rois)
        return out, state
