"""Attention layers.

The reference has NO attention op (SURVEY §5.7: sequence handling is
``Recurrent`` unrolling only) — this module is the TPU-native long-context
extension the rebuild treats as first-class: a standard multi-head attention
whose sequence dimension can be sharded across the mesh's ``seq`` axis via
ring attention (``bigdl_tpu/parallel/ring_attention.py``).

Shapes follow (batch, time, dim); heads split the last dim.  All matmuls are
batched (B*H GEMMs) so XLA tiles them onto the MXU.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from bigdl_tpu.nn import init as init_methods
from bigdl_tpu.nn.module import Module


def _axis_bound(name: str) -> bool:
    """Trace-time check: is the named mesh axis currently bound (are we
    inside a shard_map/pmap over it)?"""
    try:
        jax.lax.axis_index(name)
        return True
    except Exception:
        return False


def scaled_dot_product_attention(q: jnp.ndarray, k: jnp.ndarray,
                                 v: jnp.ndarray,
                                 causal: bool = False,
                                 mask: Optional[jnp.ndarray] = None
                                 ) -> jnp.ndarray:
    """(B, T, H, Dh) q/k/v -> (B, T, H, Dh); softmax over the key axis."""
    dh = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(dh)
    # finite mask value: a fully-masked row (all-padding) must softmax to
    # uniform junk rather than NaN (-inf rows give 0/0)
    neg_big = jnp.asarray(jnp.finfo(scores.dtype).min, scores.dtype)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        cm = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        scores = jnp.where(cm[None, None], scores, neg_big)
    if mask is not None:
        scores = jnp.where(mask, scores, neg_big)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def paged_attention(q: jnp.ndarray, k_ctx: jnp.ndarray, v_ctx: jnp.ndarray,
                    valid: jnp.ndarray) -> jnp.ndarray:
    """Decode-step attention over a gathered paged-cache context.

    ``q`` is the current step's query, (B, T, H, Dh) with T=1 on the
    decode path; ``k_ctx``/``v_ctx`` are the (B, S, H, Dh) context rows
    gathered from the KV pool via each sequence's block table (S = the
    table capacity in tokens, mostly padding for short sequences);
    ``valid`` is the (B, S) mask of real context positions.  Numerics are
    exactly :func:`scaled_dot_product_attention` with an explicit mask:
    the finite mask value makes an invalid key's probability underflow to
    0.0, so a padded context attends identically to the unpadded one —
    the decode-vs-full-forward parity proof leans on this.  A fully
    masked row (an inactive decode slot) softmaxes to uniform junk
    rather than NaN; its output is discarded on the host."""
    return scaled_dot_product_attention(q, k_ctx, v_ctx, causal=False,
                                        mask=valid[:, None, None, :])


def chunked_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      causal: bool = False, chunk: int = 1024
                      ) -> jnp.ndarray:
    """Query-chunked attention in pure XLA: identical numerics to
    :func:`scaled_dot_product_attention`, O(T * chunk) score memory
    instead of O(T^2).

    A ``lax.scan`` walks the query blocks; each step attends its block
    against the FULL key/value (one big MXU-shaped matmul pair), and the
    step body is ``jax.checkpoint``-ed so the backward pass rematerializes
    each block's scores instead of saving all of them — without that, the
    scan VJP would stash every step's (B, H, chunk, T) probability matrix
    and reinstate the O(T^2) footprint.

    This is the single-chip fallback for shapes where the one-shot
    standard path's O(T^2) program crashes the backend compiler (measured
    at T16384: ``docs/longctx_t16384_repro.md``) and the pallas kernel's
    constraints (head_dim % 128, TPU-only) don't hold.  For causal masks
    it still computes the fully-masked upper blocks (~2x the minimal
    FLOPs) — static shapes keep XLA happy; the pallas flash kernel is the
    path that skips them."""
    bsz, t, h, dh = q.shape
    tk = k.shape[1]
    if t % chunk != 0:
        raise ValueError(f"chunked attention needs T divisible by the "
                         f"chunk size: T={t}, chunk={chunk}")
    nq = t // chunk
    scale = 1.0 / math.sqrt(dh)
    neg_big = jnp.asarray(jnp.finfo(q.dtype).min, q.dtype)
    k_pos = jnp.arange(tk)
    # (nq, B, chunk, H, Dh) so scan's leading axis is the q-block index
    qb = jnp.moveaxis(q.reshape(bsz, nq, chunk, h, dh), 1, 0)

    @jax.checkpoint
    def step(_, qi):
        i, qc = qi
        scores = jnp.einsum("bqhd,bkhd->bhqk", qc, k) * scale
        if causal:
            # bottom-right aligned like scaled_dot_product_attention's
            # tril(k=tk-tq): for Tq != Tkv, query i attends keys up to
            # i + (tk - t)
            q_pos = i * chunk + jnp.arange(chunk) + (tk - t)
            allowed = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(allowed[None, None], scores, neg_big)
        p = jax.nn.softmax(scores, axis=-1)
        return None, jnp.einsum("bhqk,bkhd->bqhd", p, v)
    _, ob = lax.scan(step, None, (jnp.arange(nq), qb))
    return jnp.moveaxis(ob, 0, 1).reshape(bsz, t, h, dh)


def _flash_block_sizes(t: int):
    """Measured v5e tile sizes for the pallas flash kernel (r5,
    ``_flash_tune`` protocol, B1/H8/Dh128, fwd+bwd, carried chain):

    ======  ==========  =========  ==========
    tiles   T8192       T16384     speedup
    ======  ==========  =========  ==========
    128     32.6 ms     139.1 ms   1.0x (stock default)
    512     10.8 ms      27.0 ms   3.0-5.2x
    1024     8.4 ms      22.0 ms   3.9-6.3x
    2048    compile-helper crash (same class as the T16384 standard
            path, docs/longctx_t16384_repro.md)
    ======  ==========  =========  ==========

    The stock default (every tile 128) starves the kernel; 1024-square
    tiles are the measured optimum at every shape that compiles.  Tiles
    must divide the sequence length, so shorter/odd T fall back through
    the power-of-two ladder."""
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes
    blk = 128
    for cand in (1024, 512, 256):
        if t % cand == 0:
            blk = cand
            break
    return BlockSizes(
        block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
        block_q_major_dkv=blk, block_k_major_dkv=blk,
        block_k_dkv=blk, block_q_dkv=blk,
        block_k_major_dq=blk, block_k_dq=blk, block_q_dq=blk)


class MultiHeadAttention(Module):
    """Self-attention over (B, T, D) input; table input (q_src, kv_src)
    gives cross-attention.

    ``flash``: opt-in TPU pallas flash-attention kernel with v5e-tuned
    tile sizes (:func:`_flash_block_sizes` — the stock 128 defaults are
    3.9-6.3x slower and the reason earlier rounds measured flash losing).
    Measured r5 in the full jitted train step: flash WINS at every
    realistic shape tried — T2048/B8 +21%, 537M/T2048 +17% (76.0%
    MFU), T8192 1.86x, T16384 65k tok/s where the one-shot standard path
    exhausts HBM on saved O(T^2) residuals beyond 2 layers
    (docs/longctx_t16384_repro.md; ``chunk`` or per-block remat also
    recover that shape).  Default (False) stays the standard path — it
    is bit-exact against the other paths, composes with the GSPMD head
    split (pallas kernels do not partition), and has no shape
    constraints; perf-critical dense training opts in (bench.py's LM
    legs do).  flash=True raises when the backend/shape constraints
    aren't met (TPU only, T % 128 == 0, head_dim % 128 == 0,
    self-attention with Tq == Tkv — the kernel's causal mask is
    top-left aligned, which diverges from the reference's
    bottom-right-aligned mask when Tq != Tkv).  Revisit per hardware
    generation.

    ``chunk=N``: the pure-XLA q-blockwise path (:func:`chunked_attention`)
    — same numerics as standard (incl. the bottom-right-aligned causal
    mask for Tq != Tkv), O(T*N) score memory; the second long-context
    path where pallas is unwanted (e.g. under the GSPMD head split,
    which pallas kernels cannot partition)."""

    # class-level defaults keep OLD pickled snapshots forward-loadable:
    # Module.__setstate__ dict-updates, so instances serialized before an
    # attribute existed fall through to these
    flash = False
    chunk: Optional[int] = None
    sequence_parallel: Optional[str] = None
    #: mesh-axis name for the EXPLICIT (shard_map) Megatron head split —
    #: the pipeline x tp composition; GSPMD meshes use tp_specs instead
    model_parallel: Optional[str] = None

    def __init__(self, hidden_size: int, n_head: int, causal: bool = False,
                 with_bias: bool = True, flash: bool = False,
                 chunk: Optional[int] = None, name=None):
        super().__init__(name)
        if hidden_size % n_head != 0:
            raise ValueError(f"hidden {hidden_size} % heads {n_head} != 0")
        if flash and chunk:
            raise ValueError("flash and chunk are alternative long-context "
                             "paths; pick one")
        self.hidden_size = hidden_size
        self.n_head = n_head
        self.head_dim = hidden_size // n_head
        self.causal = causal
        self.with_bias = with_bias
        self.flash = flash
        # chunk=N: q-blockwise scan attention (pure XLA; see
        # chunked_attention) — the second long-context path, for shapes
        # where one-shot O(T^2) breaks the backend and pallas is unwanted
        self.chunk = chunk
        # mesh-axis name for ring-attention sequence parallelism; the ring
        # path engages only while that named axis is bound (i.e. inside a
        # shard_map over the mesh's seq axis — DistriOptimizer sets this
        # for sequence-parallel training); plain forwards are unaffected
        self.sequence_parallel: Optional[str] = None

    def set_sequence_parallel(self, axis_name: Optional[str]
                              ) -> "MultiHeadAttention":
        if axis_name and self.flash:
            raise ValueError("flash kernel and ring sequence parallelism "
                             "are mutually exclusive")
        if axis_name and self.model_parallel:
            raise ValueError("pick one of model_parallel / "
                             "sequence_parallel per attention layer")
        self.sequence_parallel = axis_name
        self._jit_apply = None
        return self

    def set_model_parallel(self, axis_name: Optional[str]
                           ) -> "MultiHeadAttention":
        """Explicit Megatron head split over the named mesh axis (engages
        only while that axis is bound — the shard_map pipeline x tp step):
        wq/wk/wv are column-split so each device computes its local heads,
        wo is row-split with the pair's single psum on the output."""
        if axis_name and self.flash:
            raise ValueError("flash kernel is incompatible with the "
                             "Megatron head split (pallas kernels do not "
                             "partition)")
        if axis_name and self.sequence_parallel:
            raise ValueError("pick one of model_parallel / "
                             "sequence_parallel per attention layer")
        self.model_parallel = axis_name
        self._jit_apply = None
        return self

    def _flash_ok(self, q, k) -> bool:
        """Static (trace-time) eligibility for the pallas kernel.  Only
        explicit ``flash=True`` engages it (see class docstring)."""
        if not self.flash:
            return False
        try:
            from jax.experimental.pallas.ops.tpu import flash_attention  # noqa: F401
        except ImportError:
            ok = False
        else:
            ok = (jax.default_backend() == "tpu" and
                  q.shape[1] == k.shape[1] and
                  q.shape[1] % 128 == 0 and
                  self.head_dim % 128 == 0)
        if not ok:
            raise ValueError(
                "flash=True needs a TPU backend, equal q/kv sequence "
                "lengths divisible by 128, and head_dim divisible by 128 "
                f"(got q {q.shape}, k {k.shape}, head_dim {self.head_dim})")
        return ok

    def _init_params(self, rng):
        ks = jax.random.split(rng, 4)
        d = self.hidden_size
        xavier = init_methods.Xavier()
        p = {}
        for key, name in zip(ks, ("wq", "wk", "wv", "wo")):
            p[name] = xavier(key, (d, d), d, d)
        if self.with_bias:
            for name in ("bq", "bk", "bv", "bo"):
                p[name] = jnp.zeros((d,))
        return p

    def _project(self, params, x, w, b):
        y = x @ params[w]
        if self.with_bias:
            y = y + params[b]
        bsz, t, _ = y.shape
        # -1 heads: under the explicit Megatron split params hold only
        # the LOCAL heads' columns (head_dim never splits)
        return y.reshape(bsz, t, -1, self.head_dim)

    # -- decode-cache apply path (serving/lm.py) --------------------------

    def project_step(self, params, x):
        """Project one decode/prefill span into per-head q/k/v, each
        (B, T, H, Dh) — the serving path's entry into this module's
        weights: the caller scatters k/v into the paged pool between
        projection and attention (the current token must be IN the cache
        before the gather so it attends itself)."""
        q = self._project(params, x, "wq", "bq")
        k = self._project(params, x, "wk", "bk")
        v = self._project(params, x, "wv", "bv")
        return q, k, v

    def attend_cached(self, params, q, k_ctx, v_ctx, valid):
        """Single-step attention over the gathered paged context plus
        this module's output projection: (B, T, H, Dh) q against
        (B, S, H, Dh) context under the (B, S) validity mask ->
        (B, T, D).  Same numerics as :meth:`apply`'s standard path —
        masked keys underflow to exact zero probability."""
        out = paged_attention(q, k_ctx, v_ctx, valid)
        bsz, t = out.shape[0], out.shape[1]
        out = out.reshape(bsz, t, -1) @ params["wo"]
        if self.with_bias:
            out = out + params["bo"]
        return out

    def apply(self, params, input, state, training=False, rng=None):
        if isinstance(input, (list, tuple)):
            q_src, kv_src = input[0], input[1]
        else:
            q_src = kv_src = input
        tp_axis = self.model_parallel
        if not (tp_axis and _axis_bound(tp_axis)):
            tp_axis = None
        q = self._project(params, q_src, "wq", "bq")
        k = self._project(params, kv_src, "wk", "bk")
        v = self._project(params, kv_src, "wv", "bv")
        if self.sequence_parallel and _axis_bound(self.sequence_parallel):
            if q_src is not kv_src:
                raise ValueError("sequence-parallel MHA is self-attention "
                                 "only (q and kv must be the same source)")
            from bigdl_tpu.parallel.ring_attention import (
                _ring_attention_shard)
            out = _ring_attention_shard(q, k, v,
                                        axis_name=self.sequence_parallel,
                                        causal=self.causal)
        elif self.chunk:
            out = chunked_attention(q, k, v, causal=self.causal,
                                    chunk=self.chunk)
        elif self._flash_ok(q, k):
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention)
            out = flash_attention(
                jnp.transpose(q, (0, 2, 1, 3)),
                jnp.transpose(k, (0, 2, 1, 3)),
                jnp.transpose(v, (0, 2, 1, 3)),
                causal=self.causal,
                sm_scale=1.0 / math.sqrt(self.head_dim),
                block_sizes=_flash_block_sizes(q.shape[1]))
            out = jnp.transpose(out, (0, 2, 1, 3))
        else:
            out = scaled_dot_product_attention(q, k, v, causal=self.causal)
        # names the attention context for selective rematerialization:
        # nn.Remat(policy="save_attn") saves THIS tensor (O(B*T*d) per
        # block) so the VJP recomputes only projections/elementwise, never
        # the attention kernel itself.  A no-op outside jax.checkpoint.
        out = checkpoint_name(out, "attn_ctx")
        bsz, t = out.shape[0], out.shape[1]
        # -1: local heads * head_dim under the explicit Megatron split
        out = out.reshape(bsz, t, -1) @ params["wo"]
        if tp_axis:
            out = lax.psum(out, tp_axis)   # the head-split pair's one psum
        if self.with_bias:
            out = out + params["bo"]
        return out, state
