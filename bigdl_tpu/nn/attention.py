"""Attention layers.

The reference has NO attention op (SURVEY §5.7: sequence handling is
``Recurrent`` unrolling only) — this module is the TPU-native long-context
extension the rebuild treats as first-class: a standard multi-head attention
whose sequence dimension can be sharded across the mesh's ``seq`` axis via
ring attention (``bigdl_tpu/parallel/ring_attention.py``).

Shapes follow (batch, time, dim); heads split the last dim.  All matmuls are
batched (B*H GEMMs) so XLA tiles them onto the MXU.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import init as init_methods
from bigdl_tpu.nn.module import Module


def scaled_dot_product_attention(q: jnp.ndarray, k: jnp.ndarray,
                                 v: jnp.ndarray,
                                 causal: bool = False,
                                 mask: Optional[jnp.ndarray] = None
                                 ) -> jnp.ndarray:
    """(B, T, H, Dh) q/k/v -> (B, T, H, Dh); softmax over the key axis."""
    dh = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(dh)
    # finite mask value: a fully-masked row (all-padding) must softmax to
    # uniform junk rather than NaN (-inf rows give 0/0)
    neg_big = jnp.asarray(jnp.finfo(scores.dtype).min, scores.dtype)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        cm = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        scores = jnp.where(cm[None, None], scores, neg_big)
    if mask is not None:
        scores = jnp.where(mask, scores, neg_big)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


class MultiHeadAttention(Module):
    """Self-attention over (B, T, D) input; table input (q_src, kv_src)
    gives cross-attention."""

    def __init__(self, hidden_size: int, n_head: int, causal: bool = False,
                 with_bias: bool = True, name=None):
        super().__init__(name)
        if hidden_size % n_head != 0:
            raise ValueError(f"hidden {hidden_size} % heads {n_head} != 0")
        self.hidden_size = hidden_size
        self.n_head = n_head
        self.head_dim = hidden_size // n_head
        self.causal = causal
        self.with_bias = with_bias

    def _init_params(self, rng):
        ks = jax.random.split(rng, 4)
        d = self.hidden_size
        xavier = init_methods.Xavier()
        p = {}
        for key, name in zip(ks, ("wq", "wk", "wv", "wo")):
            p[name] = xavier(key, (d, d), d, d)
        if self.with_bias:
            for name in ("bq", "bk", "bv", "bo"):
                p[name] = jnp.zeros((d,))
        return p

    def _project(self, params, x, w, b):
        y = x @ params[w]
        if self.with_bias:
            y = y + params[b]
        bsz, t, _ = y.shape
        return y.reshape(bsz, t, self.n_head, self.head_dim)

    def apply(self, params, input, state, training=False, rng=None):
        if isinstance(input, (list, tuple)):
            q_src, kv_src = input[0], input[1]
        else:
            q_src = kv_src = input
        q = self._project(params, q_src, "wq", "bq")
        k = self._project(params, kv_src, "wk", "bk")
        v = self._project(params, kv_src, "wv", "bv")
        out = scaled_dot_product_attention(q, k, v, causal=self.causal)
        bsz, t = out.shape[0], out.shape[1]
        out = out.reshape(bsz, t, self.hidden_size) @ params["wo"]
        if self.with_bias:
            out = out + params["bo"]
        return out, state
