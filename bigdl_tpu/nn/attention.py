"""Attention layers.

The reference has NO attention op (SURVEY §5.7: sequence handling is
``Recurrent`` unrolling only) — this module is the TPU-native long-context
extension the rebuild treats as first-class: a standard multi-head attention
whose sequence dimension can be sharded across the mesh's ``seq`` axis via
ring attention (``bigdl_tpu/parallel/ring_attention.py``).

Shapes follow (batch, time, dim); heads split the last dim.  All matmuls are
batched (B*H GEMMs) so XLA tiles them onto the MXU.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import init as init_methods
from bigdl_tpu.nn.module import Module


def _axis_bound(name: str) -> bool:
    """Trace-time check: is the named mesh axis currently bound (are we
    inside a shard_map/pmap over it)?"""
    try:
        jax.lax.axis_index(name)
        return True
    except Exception:
        return False


def scaled_dot_product_attention(q: jnp.ndarray, k: jnp.ndarray,
                                 v: jnp.ndarray,
                                 causal: bool = False,
                                 mask: Optional[jnp.ndarray] = None
                                 ) -> jnp.ndarray:
    """(B, T, H, Dh) q/k/v -> (B, T, H, Dh); softmax over the key axis."""
    dh = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(dh)
    # finite mask value: a fully-masked row (all-padding) must softmax to
    # uniform junk rather than NaN (-inf rows give 0/0)
    neg_big = jnp.asarray(jnp.finfo(scores.dtype).min, scores.dtype)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        cm = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        scores = jnp.where(cm[None, None], scores, neg_big)
    if mask is not None:
        scores = jnp.where(mask, scores, neg_big)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


class MultiHeadAttention(Module):
    """Self-attention over (B, T, D) input; table input (q_src, kv_src)
    gives cross-attention.

    ``flash``: opt-in TPU pallas flash-attention kernel.  Measured on v5e
    across the full shape range (bench_longctx.json): XLA's fused bf16
    path wins at every shape it compiles — flash is 0.68x at T2048 and
    0.58x at T8192 in the full jitted train step — but at T16384 the
    standard path's O(T^2) program fails to compile on this backend
    while flash runs (13.9k tokens/s at d1024/L8/B1), so flash is the
    single-chip path beyond ~T8192 (multi-chip: ring attention over a
    ``seq`` axis).  Default (False) is the standard path; pass ``True``
    to require the kernel (raises when the backend/shape constraints
    aren't met; self-attention only — the kernel's causal mask is
    top-left aligned, which diverges from the reference's
    bottom-right-aligned mask when Tq != Tkv).  Revisit per hardware
    generation."""

    def __init__(self, hidden_size: int, n_head: int, causal: bool = False,
                 with_bias: bool = True, flash: bool = False, name=None):
        super().__init__(name)
        if hidden_size % n_head != 0:
            raise ValueError(f"hidden {hidden_size} % heads {n_head} != 0")
        self.hidden_size = hidden_size
        self.n_head = n_head
        self.head_dim = hidden_size // n_head
        self.causal = causal
        self.with_bias = with_bias
        self.flash = flash
        # mesh-axis name for ring-attention sequence parallelism; the ring
        # path engages only while that named axis is bound (i.e. inside a
        # shard_map over the mesh's seq axis — DistriOptimizer sets this
        # for sequence-parallel training); plain forwards are unaffected
        self.sequence_parallel: Optional[str] = None

    def set_sequence_parallel(self, axis_name: Optional[str]
                              ) -> "MultiHeadAttention":
        if axis_name and self.flash:
            raise ValueError("flash kernel and ring sequence parallelism "
                             "are mutually exclusive")
        self.sequence_parallel = axis_name
        self._jit_apply = None
        return self

    def _flash_ok(self, q, k) -> bool:
        """Static (trace-time) eligibility for the pallas kernel.  Only
        explicit ``flash=True`` engages it (see class docstring)."""
        if not self.flash:
            return False
        try:
            from jax.experimental.pallas.ops.tpu import flash_attention  # noqa: F401
        except ImportError:
            ok = False
        else:
            ok = (jax.default_backend() == "tpu" and
                  q.shape[1] == k.shape[1] and
                  q.shape[1] % 128 == 0 and
                  self.head_dim % 128 == 0)
        if not ok:
            raise ValueError(
                "flash=True needs a TPU backend, equal q/kv sequence "
                "lengths divisible by 128, and head_dim divisible by 128 "
                f"(got q {q.shape}, k {k.shape}, head_dim {self.head_dim})")
        return ok

    def _init_params(self, rng):
        ks = jax.random.split(rng, 4)
        d = self.hidden_size
        xavier = init_methods.Xavier()
        p = {}
        for key, name in zip(ks, ("wq", "wk", "wv", "wo")):
            p[name] = xavier(key, (d, d), d, d)
        if self.with_bias:
            for name in ("bq", "bk", "bv", "bo"):
                p[name] = jnp.zeros((d,))
        return p

    def _project(self, params, x, w, b):
        y = x @ params[w]
        if self.with_bias:
            y = y + params[b]
        bsz, t, _ = y.shape
        return y.reshape(bsz, t, self.n_head, self.head_dim)

    def apply(self, params, input, state, training=False, rng=None):
        if isinstance(input, (list, tuple)):
            q_src, kv_src = input[0], input[1]
        else:
            q_src = kv_src = input
        q = self._project(params, q_src, "wq", "bq")
        k = self._project(params, kv_src, "wk", "bk")
        v = self._project(params, kv_src, "wv", "bv")
        if self.sequence_parallel and _axis_bound(self.sequence_parallel):
            if q_src is not kv_src:
                raise ValueError("sequence-parallel MHA is self-attention "
                                 "only (q and kv must be the same source)")
            from bigdl_tpu.parallel.ring_attention import (
                _ring_attention_shard)
            out = _ring_attention_shard(q, k, v,
                                        axis_name=self.sequence_parallel,
                                        causal=self.causal)
        elif self._flash_ok(q, k):
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention)
            out = flash_attention(
                jnp.transpose(q, (0, 2, 1, 3)),
                jnp.transpose(k, (0, 2, 1, 3)),
                jnp.transpose(v, (0, 2, 1, 3)),
                causal=self.causal,
                sm_scale=1.0 / math.sqrt(self.head_dim))
            out = jnp.transpose(out, (0, 2, 1, 3))
        else:
            out = scaled_dot_product_attention(q, k, v, causal=self.causal)
        bsz, t = out.shape[0], out.shape[1]
        out = out.reshape(bsz, t, self.hidden_size) @ params["wo"]
        if self.with_bias:
            out = out + params["bo"]
        return out, state
