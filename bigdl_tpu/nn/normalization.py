"""Normalization layers.

Reference: ``nn/BatchNormalization.scala:50``, ``nn/SpatialBatchNormalization.scala``,
``nn/SpatialCrossMapLRN.scala``, ``nn/SpatialWithinChannelLRN.scala``,
``nn/SpatialContrastiveNormalization.scala``, ``nn/SpatialDivisiveNormalization.scala``,
``nn/SpatialSubtractiveNormalization.scala``, ``nn/Normalize.scala``.

BatchNormalization is the one stateful layer in the framework: running
mean/var live in the module *state* pytree and a fresh state is returned from
``apply`` — the functional mirror of the reference's mutable runningMean /
runningVar tensors.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.analysis.contracts import ModuleContract
from bigdl_tpu.nn.module import Module
from bigdl_tpu import ops


class BatchNormalization(Module):
    """BN over dim 1 of (N, C) input (reference ``nn/BatchNormalization.scala:50``)."""

    _reduce_axes = (0,)
    _param_shape_ndim = 2
    contract = ModuleContract(input_ndim=(2,), dtypes="float")

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True, init_weight=None, init_bias=None,
                 init_running_mean=None, init_running_var=None,
                 name=None):
        super().__init__(name)
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.init_weight = init_weight
        self.init_bias = init_bias
        # pre-trained running statistics (model importers: caffe BATCHNORM
        # stores mean/var blobs, not affine params)
        self.init_running_mean = init_running_mean
        self.init_running_var = init_running_var

    def _init_params(self, rng):
        if not self.affine:
            return {}
        w = jnp.asarray(self.init_weight) if self.init_weight is not None \
            else jax.random.uniform(rng, (self.n_output,))
        b = jnp.asarray(self.init_bias) if self.init_bias is not None \
            else jnp.zeros((self.n_output,))
        return {"weight": w, "bias": b}

    def _init_state(self):
        mean = (jnp.asarray(self.init_running_mean)
                if self.init_running_mean is not None
                else jnp.zeros((self.n_output,)))
        var = (jnp.asarray(self.init_running_var)
               if self.init_running_var is not None
               else jnp.ones((self.n_output,)))
        return {"running_mean": mean, "running_var": var}

    # channel axis (1 = torch NCHW convention; NHWC variants use -1)
    channel_axis = 1

    def _param_view(self, ndim):
        shape = [1] * ndim
        shape[self.channel_axis % ndim] = self.n_output
        return shape

    def apply(self, params, input, state, training=False, rng=None):
        view = self._param_view(input.ndim)
        ch = self.channel_axis % input.ndim
        axes = tuple(i for i in range(input.ndim) if i != ch)
        if training:
            mean = jnp.mean(input, axis=axes)
            var = jnp.var(input, axis=axes)
            n = input.size // self.n_output
            unbiased = var * n / max(1, n - 1)
            m = self.momentum
            new_state = {
                "running_mean": (1 - m) * state["running_mean"] + m * mean,
                "running_var": (1 - m) * state["running_var"] + m * unbiased,
            }
        else:
            # compute in the activation dtype: fp32 running stats must not
            # promote a bf16 inference forward back to fp32 mid-network
            mean = state["running_mean"].astype(input.dtype)
            var = state["running_var"].astype(input.dtype)
            new_state = state
        inv = jax.lax.rsqrt(var + self.eps)
        out = (input - jnp.reshape(mean, view)) * jnp.reshape(inv, view)
        if self.affine:
            out = out * jnp.reshape(params["weight"], view) \
                + jnp.reshape(params["bias"], view)
        return out, new_state


class SpatialBatchNormalization(BatchNormalization):
    """BN over (N, C, H, W) (reference ``nn/SpatialBatchNormalization.scala``).
    ``format="NHWC"`` normalizes the trailing channel axis instead (the
    TF-import and TPU-preferred activation layout)."""

    layout_role = "spatial"
    contract = ModuleContract(input_ndim=(3, 4), dtypes="float")

    def __init__(self, n_output, eps=1e-5, momentum=0.1, affine=True,
                 init_weight=None, init_bias=None, init_running_mean=None,
                 init_running_var=None, format="NCHW", name=None):
        super().__init__(n_output, eps, momentum, affine, init_weight,
                         init_bias, init_running_mean, init_running_var,
                         name=name)
        self.format = format
        self.channel_axis = 1 if format == "NCHW" else -1

    def set_format(self, format):
        super().set_format(format)
        self.channel_axis = 1 if format == "NCHW" else -1
        return self


class Normalize(Module):
    """Lp-normalise each sample (reference ``nn/Normalize.scala``)."""

    def __init__(self, p: float, eps: float = 1e-10, name=None):
        super().__init__(name)
        self.p = p
        self.eps = eps

    def apply(self, params, input, state, training=False, rng=None):
        if self.p == float("inf"):
            norm = jnp.max(jnp.abs(input), axis=-1, keepdims=True)
        else:
            norm = jnp.sum(jnp.abs(input) ** self.p, axis=-1,
                           keepdims=True) ** (1.0 / self.p)
        return input / (norm + self.eps), state


class SpatialCrossMapLRN(Module):
    """AlexNet-style local response normalization across channels
    (reference ``nn/SpatialCrossMapLRN.scala``)."""

    layout_role = "spatial"

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0, format: str = "NCHW", name=None):
        super().__init__(name)
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.format = format

    def apply(self, params, input, state, training=False, rng=None):
        # window sum of squares across the channel axis (1 for NCHW, -1
        # for the channels-last path — where the window slides over the
        # MINOR axis, the layout reduce/slice ops actually like)
        ch = 1 if self.format == "NCHW" else input.ndim - 1
        sq = input * input
        half = (self.size - 1) // 2
        pads = [(0, 0)] * input.ndim
        pads[ch] = (half, self.size - 1 - half)
        padded = jnp.pad(sq, pads)
        # static unrolled window sum over the small channel window; avoids
        # lax.reduce_window over the non-minor channel dim, which the TPU
        # backend lays out poorly (and miscompiles under AOT).
        c = input.shape[ch]
        window = jax.lax.slice_in_dim(padded, 0, c, axis=ch)
        for i in range(1, self.size):
            window = window + jax.lax.slice_in_dim(padded, i, i + c, axis=ch)
        denom = (self.k + self.alpha / self.size * window) ** self.beta
        return input / denom, state


def _gaussian_kernel1d(size: int) -> np.ndarray:
    # torch's image.gaussian with default sigma=0.25 (relative), amplitude 1
    sigma = 0.25 * size
    xs = np.arange(size) - (size - 1) / 2.0
    k = np.exp(-(xs ** 2) / (2 * sigma ** 2))
    return k / k.sum()


class SpatialSubtractiveNormalization(Module):
    """Subtract weighted neighborhood mean
    (reference ``nn/SpatialSubtractiveNormalization.scala``)."""

    def __init__(self, n_input_plane: int = 1, kernel=None, name=None):
        super().__init__(name)
        self.n_input_plane = n_input_plane
        if kernel is None:
            kernel = np.outer(_gaussian_kernel1d(9), _gaussian_kernel1d(9))
        self.kernel = jnp.asarray(kernel, jnp.float32)
        self.kernel = self.kernel / jnp.sum(self.kernel)

    def _local_mean(self, input):
        kh, kw = self.kernel.shape
        c = input.shape[1]
        # one cross-channel mean map (the reference sums the kernel over
        # every input plane and divides by nInputPlane): kernel/c on each
        # of the c INPUT features of a single-output conv, same padding,
        # normalised by actual coverage at the borders
        w = jnp.tile(self.kernel[:, :, None, None] / c,
                     (1, 1, c, 1)).astype(input.dtype)
        pad = ((kh // 2, (kh - 1) - kh // 2), (kw // 2, (kw - 1) - kw // 2))
        dn = jax.lax.conv_dimension_numbers(input.shape, w.shape,
                                            ("NCHW", "HWIO", "NCHW"))
        mean = jax.lax.conv_general_dilated(
            input, w, (1, 1), pad, dimension_numbers=dn)
        # coverage correction at borders (__init__ normalized the kernel
        # to sum 1, so cov is the fraction of kernel mass inside the map)
        ones = jnp.ones((1, c) + input.shape[2:], input.dtype)
        cov = jax.lax.conv_general_dilated(
            ones, w, (1, 1), pad, dimension_numbers=dn)
        mean = mean / jnp.maximum(cov, 1e-8)
        return jnp.broadcast_to(mean, input.shape)

    def apply(self, params, input, state, training=False, rng=None):
        squeeze = input.ndim == 3
        if squeeze:
            input = input[None]
        out = input - self._local_mean(input)
        if squeeze:
            out = out[0]
        return out, state


class SpatialDivisiveNormalization(SpatialSubtractiveNormalization):
    """Divide by weighted neighborhood stddev
    (reference ``nn/SpatialDivisiveNormalization.scala``)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4, name=None):
        super().__init__(n_input_plane, kernel, name=name)
        self.threshold = threshold
        self.thresval = thresval

    def apply(self, params, input, state, training=False, rng=None):
        squeeze = input.ndim == 3
        if squeeze:
            input = input[None]
        local_var = self._local_mean(input * input)
        local_std = jnp.sqrt(jnp.maximum(local_var, 0.0))
        mean_std = jnp.mean(local_std, axis=(1, 2, 3), keepdims=True)
        adjusted = jnp.maximum(local_std, mean_std)
        adjusted = jnp.where(adjusted < self.threshold, self.thresval, adjusted)
        out = input / adjusted
        if squeeze:
            out = out[0]
        return out, state


class SpatialContrastiveNormalization(Module):
    """Subtractive then divisive normalization
    (reference ``nn/SpatialContrastiveNormalization.scala``)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4, name=None):
        super().__init__(name)
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel,
                                                threshold, thresval)

    def apply(self, params, input, state, training=False, rng=None):
        x, _ = self.sub.apply({}, input, {}, training=training, rng=rng)
        return self.div.apply({}, x, {}, training=training, rng=rng)[0], state


class SpatialWithinChannelLRN(Module):
    """LRN over a spatial window within each channel
    (reference ``nn/SpatialWithinChannelLRN.scala``)."""

    layout_role = "spatial"

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 format: str = "NCHW", name=None):
        super().__init__(name)
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.format = format

    def apply(self, params, input, state, training=False, rng=None):
        from bigdl_tpu.ops.pooling import _spatial_axes
        sq = input * input
        half_lo = self.size // 2
        half_hi = (self.size - 1) - half_lo
        h_ax, w_ax = _spatial_axes(self.format)
        pads = [(0, 0)] * 4
        pads[h_ax] = pads[w_ax] = (half_lo, half_hi)
        dims = [1] * 4
        dims[h_ax] = dims[w_ax] = self.size
        window = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add, tuple(dims), (1, 1, 1, 1), tuple(pads))
        denom = (1.0 + self.alpha / (self.size * self.size) * window) ** self.beta
        return input / denom, state
