"""Single-chip training-throughput benchmark.

Mirrors the reference's perf protocol: synthetic-input model-zoo throughput
(``models/utils/LocalOptimizerPerf.scala:82-140``) reported as the driver
log's ``Throughput is N records/second`` line
(``optim/DistriOptimizer.scala:293-297``).

Headline metric: ResNet-50/ImageNet training images/sec on one chip via the
production fused train step (forward + loss + backward + SGD update in one
jit).  Prints ONE JSON line on stdout; per-model details go to stderr.

``vs_baseline``: the reference publishes no numbers (BASELINE.json
``published: {}``), so the baseline is self-measured and pinned in
``bench_baseline.json`` at the repo root — the first measured round wrote it;
later rounds regress against it.  Without that file, vs_baseline = 1.0.
"""

import argparse
import json
import os
import sys
import time

import numpy as np


def _log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_train_step(model, criterion, optim_method, hyper, module=None,
                     precision=None):
    """The production fused step — identical shape to
    LocalOptimizer._build_step: forward (at the requested precision) + loss
    (+ regularizers) + backward + the OptimMethod's pure update, one jit."""
    import jax
    from bigdl_tpu.optim.optimizer import (mixed_precision_forward,
                                           regularization_penalty)

    reg_module = module if module is not None else model

    def step(params, slots, mstate, inputs, targets):
        def loss_fn(p):
            out, new_mstate = mixed_precision_forward(
                model, p, inputs, mstate, precision, True, None)
            loss = criterion.apply(out, targets)
            loss = loss + regularization_penalty(reg_module, p)
            return loss, new_mstate

        (loss, new_mstate), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_slots = optim_method.pure_update(grads, params, slots,
                                                         hyper)
        return new_params, new_slots, new_mstate, loss

    return jax.jit(step, donate_argnums=(0, 1, 2))


def bench_model(model, batch, input_shape, n_classes, steps=10, warmup=3,
                flops_per_image=None, logits=False, precision=None,
                criterion=None, make_batch=None):
    """Measure the fused-train-step throughput of ``model``.

    ``make_batch(rng, batch) -> (x, y)`` overrides the default
    image-classification batch (token LMs etc.); ``criterion`` overrides
    ClassNLL.  One measurement protocol for every benched model — the
    donated-carry sync subtleties live only here."""
    import jax
    import jax.numpy as jnp
    import bigdl_tpu.nn as nn

    from bigdl_tpu.optim import SGD

    model.training()
    model._ensure_init()
    criterion = criterion or nn.ClassNLLCriterion()
    # momentum SGD: the reference zoo's training configuration
    method = SGD(learning_rate=0.01, momentum=0.9)
    # ClassNLLCriterion expects log-probabilities; builders that end in bare
    # Linear logits (imagenet variants) get a LogSoftMax appended in-step.
    target = _WithLogSoftMax(model, nn.LogSoftMax()) if logits else model
    step_fn = build_train_step(target, criterion, method, method.hyper(),
                               module=model, precision=precision)

    rng = np.random.RandomState(0)
    if make_batch is not None:
        x, y = make_batch(rng, batch)
        x, y = jnp.asarray(x), jnp.asarray(y)
    else:
        x = jnp.asarray(rng.uniform(-1, 1, size=(batch,) + input_shape)
                        .astype(np.float32))
        y = jnp.asarray(rng.randint(1, n_classes + 1, size=batch)
                        .astype(np.float32))

    params, mstate = model.params, model.state
    slots = method.init_slots(params)
    t_compile = time.time()
    params, slots, mstate, loss = step_fn(params, slots, mstate, x, y)
    float(loss)
    _log(f"  compile+first step: {time.time() - t_compile:.1f}s")

    for _ in range(warmup - 1):
        params, slots, mstate, loss = step_fn(params, slots, mstate, x, y)
    float(loss)

    t0 = time.time()
    for _ in range(steps):
        params, slots, mstate, loss = step_fn(params, slots, mstate, x, y)
    # a host read of the final loss forces the whole donated-carry chain
    loss_v = float(loss)
    dt = time.time() - t0

    imgs_per_sec = batch * steps / dt
    out = {"images_per_sec": imgs_per_sec, "step_ms": dt / steps * 1e3,
           "loss": loss_v}
    if flops_per_image:
        out["tflops"] = imgs_per_sec * flops_per_image / 1e12
    return out


class _WithLogSoftMax:
    """Append log-softmax to a logits model without mutating it."""

    def __init__(self, model, lsm):
        self._m, self._lsm = model, lsm

    def apply(self, p, x, s, training=False, rng=None):
        out, new_s = self._m.apply(p, x, s, training=training, rng=rng)
        out, _ = self._lsm.apply({}, out, {})
        return out, new_s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--precision", choices=["fp32", "bf16"], default="bf16",
                    help="compute precision of the fused step (bf16 is the "
                         "TPU-first default: MXU-native, fp32 master weights)")
    ap.add_argument("--quick", action="store_true",
                    help="LeNet only (CI smoke)")
    args = ap.parse_args()

    import jax
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    _log(f"devices: {jax.devices()}")

    from bigdl_tpu.models.resnet import resnet, model_init, DatasetType

    if args.quick:
        # LeNet/MNIST (BASELINE config #1 shape) — CI smoke.  The
        # historical >11-min pathological XLA compile at batch 512 was
        # the conv WEIGHT gradient for the 1-channel 5x5 conv; the
        # small-taps slice-stack matmul path (ops/convolution.py
        # _conv2d_smallk) fixed it: full fused step now compiles in
        # ~7 s and runs ~37k img/s at batch 512.
        from bigdl_tpu.models.lenet import lenet5
        r = bench_model(lenet5(10), 512, (28, 28), 10, steps=args.steps)
        _log(f"lenet (batch 512): {r}")
        result = {"metric": "lenet_train_images_per_sec",
                  "value": round(r["images_per_sec"], 1),
                  "unit": "images/sec", "vs_baseline": 1.0}
        print(json.dumps(result))
        return

    # Long-context flagship leg: a REALISTIC LM shape — 134M params,
    # d1024/L8/T2048/B8 bf16 (head_dim 128) — through the same fused
    # step.  Measured r3 on one v5e: ~107k tokens/s = ~55% MFU (the
    # earlier d256/T512 toy leg sat at ~6%: latency-bound, not a model
    # of anything).  Flash attention RE-measured at THIS shape is still
    # slower than XLA's fused path (67k vs 99k tokens/s at B4), so the
    # default attention stays; see bench_lm.json for the pinned record.
    # Failures here must not touch the headline metric.
    try:
        import jax as _jax
        import bigdl_tpu.nn as nn
        from bigdl_tpu.models.transformer import transformer_lm

        v, d, nl, h, t, b = 16384, 1024, 8, 8, 2048, 8
        lm = transformer_lm(v, d_model=d, n_head=h, n_layers=nl, max_len=t)
        r_lm = bench_model(
            lm, b, (t,), v, steps=args.steps,
            precision="bf16",
            criterion=nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                                  size_average=True),
            make_batch=lambda rng, bsz: (
                rng.randint(1, v + 1, (bsz, t)).astype(np.float32),
                rng.randint(1, v + 1, (bsz, t)).astype(np.float32)))
        toks = r_lm["images_per_sec"] * t
        n_params = sum(int(np.prod(l.shape))
                       for l in _jax.tree_util.tree_leaves(lm.params))
        # training matmul FLOPs/token: 6*params + attention 12*L*d*T;
        # bf16 peak of one v5e chip ~197 TFLOP/s
        mfu = toks * (6 * n_params + 12 * nl * d * t) / 197e12
        _log(f"transformer-lm (B{b} T{t} d{d} L{nl} vocab {v}, "
             f"{n_params / 1e6:.0f}M params, bf16): {toks:,.0f} tokens/s "
             f"({r_lm['step_ms']:.1f} ms/step, MFU {mfu * 100:.1f}%)")
        lm_record = {"metric": "transformer_lm_train_tokens_per_sec",
                     "value": round(toks, 0), "unit": "tokens/sec",
                     "mfu": round(mfu, 3),
                     "config": {"batch": b, "seq_len": t, "d_model": d,
                                "n_layers": nl, "n_head": h, "vocab": v,
                                "params_m": round(n_params / 1e6, 1),
                                "precision": "bf16"}}
        base_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "bench_baseline.json")
        if os.path.exists(base_path):
            with open(base_path) as f:
                pinned = json.load(f).get(
                    "transformer_lm_train_tokens_per_sec")
            if pinned:
                lm_record["vs_baseline"] = round(toks / pinned, 3)
                _log(f"  lm vs pinned baseline: {toks / pinned:.3f}")
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "bench_lm.json"), "w") as f:
            json.dump(lm_record, f, indent=1)
    except Exception as e:  # diagnostic only
        _log(f"transformer-lm bench skipped: {e}")

    # ResNet-50/ImageNet synthetic — the north-star protocol.
    # ~4.09 GFLOPs/image forward; training ~3x forward.
    precision = None if args.precision == "fp32" else args.precision
    model = model_init(resnet(1000, depth=50, dataset=DatasetType.IMAGENET))
    r50 = bench_model(model, args.batch, (3, 224, 224), 1000,
                      steps=args.steps, flops_per_image=3 * 4.09e9,
                      logits=True, precision=precision)
    _log(f"resnet50 (batch {args.batch}, {args.precision}): {r50}")
    if "tflops" in r50:
        # bf16 peak of one v5e chip ~197 TFLOP/s
        _log(f"  achieved {r50['tflops']:.1f} TFLOP/s "
             f"(~{r50['tflops'] / 197 * 100:.1f}% MFU of a v5e chip)")

    value = r50["images_per_sec"]
    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "bench_baseline.json")
    vs = 1.0
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f)
        # only comparable at the batch size/precision the baseline pinned
        # baselines written before the precision field existed were fp32
        if (base.get("resnet50_train_images_per_sec") and
                base.get("batch") == args.batch and
                base.get("precision", "fp32") == args.precision):
            vs = value / base["resnet50_train_images_per_sec"]

    print(json.dumps({"metric": "resnet50_train_images_per_sec",
                      "value": round(value, 1), "unit": "images/sec",
                      "vs_baseline": round(vs, 3)}))


if __name__ == "__main__":
    main()
